"""Per-proxy config-snapshot manager: the mesh control→data seam.

Re-design of ``agent/proxycfg/manager.go:37`` + ``state.go``: for every
connect-proxy service registered with the local agent, a state machine
watches everything that proxy's data plane needs —

  CA roots          (cache: connect-ca-roots, blocking refresh)
  its leaf cert     (re-signed when the active root changes or the
                    cert approaches expiry — cache-types/
                    connect_ca_leaf.go semantics)
  intentions        (cache: intention-match on the destination, with
                    the cluster's default decision riding along)
  upstream chains   (cache: discovery-chain per upstream)
  upstream health   (cache: health-services with connect=True per
                    chain target, re-reconciled when a chain changes —
                    state.go resetWatchesFromChain)

and assembles a versioned ConfigSnapshot.  Consumers (the built-in L4
proxy via the agent HTTP API, tests, a future xDS-alike) wait on
``wait(proxy_id, min_version)`` — the same longpoll shape as a
blocking query — or iterate ``watch()``.

The reference streams Envoy protobufs over gRPC (``xds/server.go:475``);
here the snapshot is a plain dict and the "stream" is the agent's
blocking HTTP endpoint ``/v1/agent/connect/proxy/<id>`` — a deliberate
re-design: one wire codec for the whole framework, no protobuf codegen.
"""

from __future__ import annotations

import asyncio
import datetime
import logging
from typing import AsyncIterator, Optional

from consul_tpu.agent.cache import (
    CONNECT_CA_ROOTS,
    DISCOVERY_CHAIN,
    FEDERATION_MESH_GATEWAYS,
    HEALTH_SERVICES,
    INTENTION_MATCH,
    SERVICE_KIND_NODES,
)

log = logging.getLogger("consul_tpu.proxycfg")

# Re-sign the leaf when less than this fraction of its lifetime remains
# (cache-types/connect_ca_leaf.go renews within an expiry window).
LEAF_RENEW_FRACTION = 0.5


class _ProxyState:
    """One proxy's watch set + snapshot assembly (proxycfg/state.go)."""

    def __init__(self, manager: "ProxyConfigManager", proxy_id: str,
                 service: dict):
        self.m = manager
        self.proxy_id = proxy_id
        self.service = service
        proxy = service.get("proxy") or {}
        self.destination = proxy.get("destination_service") or \
            service["service"].removesuffix("-proxy")
        self.upstreams: list[dict] = list(proxy.get("upstreams") or [])
        self.local_service_address = proxy.get(
            "local_service_address",
            f"127.0.0.1:{proxy.get('local_service_port', 0)}")

        self.version = 0
        self.snapshot: Optional[dict] = None
        self.changed = asyncio.Event()     # wakes wait()ers
        self._wake = asyncio.Event()       # wakes the assembly loop
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self._leaf: Optional[dict] = None
        self._health_watched: set[str] = set()

    # -- watch plumbing -------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        # Wake blocked wait()ers so they observe the deregistration
        # instead of sleeping out their longpoll (an HTTP server
        # draining handlers would otherwise stall on them).
        self.changed.set()

    async def _run(self) -> None:
        cache = self.m.cache
        # Prime + subscribe the static sources; health watches are
        # reconciled per chain below.
        cache.notify(CONNECT_CA_ROOTS, {}, self._queue)
        cache.notify(INTENTION_MATCH, {"destination": self.destination},
                     self._queue)
        for up in self.upstreams:
            cache.notify(DISCOVERY_CHAIN,
                         {"name": up["destination_name"]}, self._queue)
        while True:
            try:
                await self._assemble()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - keep the proxy served
                log.exception("proxycfg %s: assembly failed", self.proxy_id)
                await asyncio.sleep(0.5)
                continue
            # Wait for any watched source to change (coalesce a burst).
            await self._queue.get()
            while not self._queue.empty():
                self._queue.get_nowait()

    # -- leaf lifecycle -------------------------------------------------

    def _leaf_stale(self, active_root_id: str) -> bool:
        if self._leaf is None:
            return True
        if self._leaf.get("root_id") != active_root_id:
            return True  # root rotated: roll the cert
        try:
            expires = datetime.datetime.fromisoformat(
                self._leaf["valid_before"])
            issued = datetime.datetime.fromisoformat(
                self._leaf.get("valid_after", self._leaf["valid_before"]))
            life = (expires - issued).total_seconds()
            left = (expires - datetime.datetime.now(datetime.timezone.utc)
                    ).total_seconds()
            return life > 0 and left < life * LEAF_RENEW_FRACTION
        except (KeyError, ValueError):
            return False

    # -- assembly -------------------------------------------------------

    async def _assemble(self) -> None:
        cache, rpc = self.m.cache, self.m.rpc
        roots_out = await cache.get(CONNECT_CA_ROOTS, {})
        roots = roots_out.get("roots") or []
        active_root_id = next(
            (r["id"] for r in roots if r.get("active")), "")

        if self._leaf_stale(active_root_id):
            out = await rpc("ConnectCA.Sign",
                            {"service": self.destination})
            self._leaf = out["leaf"]

        intent_out = await cache.get(
            INTENTION_MATCH, {"destination": self.destination})

        ups: dict[str, dict] = {}
        for up in self.upstreams:
            name = up["destination_name"]
            chain_out = await cache.get(DISCOVERY_CHAIN, {"name": name})
            chain = chain_out.get("chain") or {}
            instances: dict[str, list[dict]] = {}
            for tid, target in (chain.get("targets") or {}).items():
                remote = target["datacenter"] != self.m.datacenter
                mode = target.get("mesh_gateway", "default")
                if remote and mode in ("local", "remote"):
                    # WAN federation through mesh gateways
                    # (proxycfg/state.go resetWatchesFromChain →
                    # mesh-gateway watches; endpoints.go
                    # makeUpstreamLoadAssignmentForMeshGateway): dial a
                    # gateway instead of the instances — the LOCAL DC's
                    # gateways in local mode, the TARGET DC's (WAN
                    # addresses, via federation state) in remote mode.
                    instances[tid] = await self._gateway_endpoints(
                        mode, target["datacenter"])
                    continue
                req = {"service": target["service"], "connect": True,
                       "passing_only": True}
                if remote:
                    req["dc"] = target["datacenter"]
                hkey = f"{target['service']}@{target['datacenter']}"
                if hkey not in self._health_watched:
                    # state.go resetWatchesFromChain: new chain targets
                    # grow the watch set (stale ones age out of the
                    # cache on their own).
                    cache.notify(HEALTH_SERVICES, req, self._queue)
                    self._health_watched.add(hkey)
                health_out = await cache.get(HEALTH_SERVICES, req)
                instances[tid] = [
                    self._endpoint(row)
                    for row in health_out.get("nodes") or []
                ]
            ups[name] = {
                "chain": chain,
                "instances": instances,
                "local_bind_port": up.get("local_bind_port", 0),
                "local_bind_address": up.get("local_bind_address",
                                             "127.0.0.1"),
                "datacenter": up.get("datacenter", ""),
            }

        self.version += 1
        self.snapshot = {
            "proxy_id": self.proxy_id,
            "destination_service": self.destination,
            "datacenter": self.m.datacenter,
            "local_service_address": self.local_service_address,
            "roots": roots,
            "active_root_id": active_root_id,
            "leaf": self._leaf,
            "intentions": intent_out.get("intentions") or [],
            "default_allow": bool(intent_out.get("default_allow", True)),
            "upstreams": ups,
        }
        self.changed.set()
        self.changed = asyncio.Event()

    async def _gateway_endpoints(self, mode: str,
                                 target_dc: str) -> list[dict]:
        """Mesh-gateway endpoints for a gateway-routed upstream, with a
        live watch so assembly re-runs as gateways come and go.

        local mode   this DC's own gateways, straight from the local
                     catalog (health-watched — a freshly registered
                     gateway is visible immediately, and the watch fires
                     on changes)
        remote mode  the TARGET DC's gateways (WAN addresses) from the
                     replicated federation-state map, watched through
                     the federation-mesh-gateways cache type
        """
        from consul_tpu.connect.gateways import (
            KIND_MESH_GATEWAY,
            gateway_endpoint,
        )

        cache = self.m.cache
        if mode == "local":
            # KIND-indexed health-aware catalog watch: any local mesh
            # gateway routes service traffic regardless of its service
            # name or wanfed meta (the wanfed:1 gate belongs to the
            # SERVER plane's gateway_locator.go, not to upstream
            # endpoints — xds/endpoints.go
            # makeUpstreamLoadAssignmentForMeshGateway uses the
            # kind-filtered CheckServiceNodes watch), but a gateway with
            # a failing check must drop out.
            req = {"kind": KIND_MESH_GATEWAY, "passing_only": True}
            if "local-gateways" not in self._health_watched:
                cache.notify(SERVICE_KIND_NODES, req, self._queue)
                self._health_watched.add("local-gateways")
            out = await cache.get(SERVICE_KIND_NODES, req)
            svcs = out.get("nodes") or []
            wan = False
        else:
            # The federation-state map only ever carries wanfed
            # gateways (the AE publisher filters) — no extra gate here.
            if "federation-gateways" not in self._health_watched:
                cache.notify(FEDERATION_MESH_GATEWAYS, {}, self._queue)
                self._health_watched.add("federation-gateways")
            out = await cache.get(FEDERATION_MESH_GATEWAYS, {})
            svcs = (out.get("gateways") or {}).get(target_dc, [])
            wan = True
        return [
            gateway_endpoint(svc, wan=wan) for svc in svcs
            if svc.get("kind") == KIND_MESH_GATEWAY
        ]

    @staticmethod
    def _endpoint(row: dict) -> dict:
        svc = row.get("service") or {}
        node = row.get("node") or {}
        return {
            "address": svc.get("address") or node.get("address", ""),
            "port": svc.get("port", 0),
            "proxy_id": svc.get("id", ""),
            "node": node.get("node", ""),
        }


class ProxyConfigManager:
    """proxycfg/manager.go Manager: tracks registered proxy services
    and owns one _ProxyState each."""

    def __init__(self, cache, rpc, datacenter: str = "dc1"):
        self.cache = cache
        self.rpc = rpc
        self.datacenter = datacenter
        self._states: dict[str, _ProxyState] = {}

    # Called from Agent.add_service / remove_service.
    def register(self, service: dict) -> None:
        if service.get("kind") != "connect-proxy":
            return
        pid = service.get("id") or service["service"]
        self.deregister(pid)
        state = _ProxyState(self, pid, service)
        self._states[pid] = state
        state.start()

    def deregister(self, proxy_id: str) -> None:
        state = self._states.pop(proxy_id, None)
        if state is not None:
            state.stop()

    def proxy_ids(self) -> list[str]:
        return list(self._states)

    def snapshot(self, proxy_id: str) -> Optional[tuple[int, dict]]:
        state = self._states.get(proxy_id)
        if state is None or state.snapshot is None:
            return None
        return state.version, state.snapshot

    async def wait(self, proxy_id: str, min_version: int = 0,
                   timeout: float = 300.0) -> Optional[tuple[int, dict]]:
        """Blocking-query shape over snapshot versions (the xDS stream
        stand-in): returns as soon as version > min_version, or the
        current snapshot at timeout."""
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            state = self._states.get(proxy_id)
            if state is None:
                return None
            # Capture the event BEFORE the version check: _assemble
            # sets-then-replaces it, so a change landing between check
            # and await still wakes us.
            ev = state.changed
            if state.snapshot is not None and state.version > min_version:
                return state.version, state.snapshot
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                return (state.version, state.snapshot) \
                    if state.snapshot is not None else None
            try:
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                pass

    async def watch(self, proxy_id: str) -> AsyncIterator[tuple[int, dict]]:
        """Async iterator of snapshot versions (manager.go Watch)."""
        version = 0
        while True:
            out = await self.wait(proxy_id, min_version=version)
            if out is None:
                return
            version, snap = out
            yield version, snap

    def stop(self) -> None:
        for state in list(self._states.values()):
            state.stop()
        self._states.clear()
