"""Mesh-gateway locator for WAN federation.

Parity model: ``agent/consul/gateway_locator.go`` — when WAN federation
via mesh gateways is enabled, a server reaches a remote datacenter by
dialing a LOCAL mesh gateway, which tunnels to a REMOTE mesh gateway in
the destination DC.  The locator answers "which gateways?" from two
sources:

  local gateways     the local catalog's ``kind == "mesh-gateway"``
                     service instances (LAN addresses)
  remote gateways    the replicated ``federation_states`` table — each
                     DC's leader publishes its own gateway set to the
                     primary (anti-entropy), and secondaries pull the
                     full map back (federation_state_replication.go)

The reference restricts wan-federation routing to gateways carrying the
``consul-wan-federation=1`` service meta (gateway_locator.go:44-47
"ONLY contain ones that have the wanfed:1 meta"); we keep the same
gate.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Optional

if TYPE_CHECKING:
    from consul_tpu.store.state import StateStore

WANFED_META = "consul-wan-federation"
KIND_MESH_GATEWAY = "mesh-gateway"


def gateway_endpoint(svc: dict, wan: bool) -> dict:
    """Advertised (address, port) for a gateway instance as a data-plane
    endpoint dict.  WAN side prefers tagged_addresses["wan"]
    (structs.ServiceGatewayVirtualIPTag conventions); LAN side the
    service address, falling back to the node address."""
    tagged = svc.get("tagged_addresses") or {}
    addr, port = "", svc.get("port", 0)
    if wan and tagged.get("wan"):
        t = tagged["wan"]
        if isinstance(t, dict):
            addr, port = t.get("address", ""), t.get("port", port)
        else:
            addr = str(t)
    if not addr:
        addr = svc.get("address") or svc.get("node_address") or ""
    return {
        "address": addr, "port": port,
        "proxy_id": svc.get("id", ""),
        "node": svc.get("node", ""),
        "mesh_gateway": True,
    }


def _gateway_addr(svc: dict, wan: bool) -> str:
    ep = gateway_endpoint(svc, wan)
    return f"{ep['address']}:{ep['port']}"


class GatewayLocator:
    """gateway_locator.go GatewayLocator (pull-based redesign: the
    reference maintains push-updated sorted slices under locks; here
    every read recomputes from the single-writer state store, which is
    already index-watched and cheap at catalog scale)."""

    def __init__(self, store: "StateStore", datacenter: str,
                 primary_datacenter: str):
        self.store = store
        self.datacenter = datacenter
        self.primary_datacenter = primary_datacenter or datacenter

    # -- catalog side ---------------------------------------------------

    def local_gateway_services(self, wanfed_only: bool = False) -> list[dict]:
        _, svcs = self.store.services_by_kind(KIND_MESH_GATEWAY)
        if wanfed_only:
            svcs = [s for s in svcs
                    if (s.get("meta") or {}).get(WANFED_META) == "1"]
        return svcs

    def local_gateways(self) -> list[str]:
        """LAN addresses of this DC's wanfed mesh gateways
        (gateway_locator.go listGateways(false))."""
        return sorted({
            _gateway_addr(s, wan=False)
            for s in self.local_gateway_services(wanfed_only=True)
        })

    # -- federation-state side ------------------------------------------

    def gateways_for_dc(self, dc: str) -> list[str]:
        """WAN addresses of a remote DC's mesh gateways, as published
        in its federation state."""
        if dc == self.datacenter:
            return self.local_gateways()
        _, state = self.store.federation_state_get(dc)
        if not state:
            return []
        return sorted({
            _gateway_addr(s, wan=True)
            for s in state.get("mesh_gateways", [])
        })

    def primary_gateways(self) -> list[str]:
        """gateway_locator.go PrimaryGatewayFallbackAddresses — the
        primary's published gateways, the bootstrap path for a
        secondary."""
        return self.gateways_for_dc(self.primary_datacenter)

    def known_datacenters(self) -> list[str]:
        _, states = self.store.federation_state_list()
        return sorted(s["datacenter"] for s in states)

    def build_own_state(self) -> Optional[dict]:
        """This DC's federation state, from the local catalog
        (leader_federation_state_ae.go FederationStateAntiEntropy
        assembles the same shape before pushing to the primary)."""
        gateways = self.local_gateway_services(wanfed_only=True)
        return {
            "datacenter": self.datacenter,
            "mesh_gateways": [
                {k: v for k, v in s.items()
                 if k not in ("create_index", "modify_index")}
                for s in gateways
            ],
        }
