"""Discovery-chain compiler: config entries → a routing graph.

Re-design of ``agent/consul/discoverychain/compile.go:56`` (Compile):
three config-entry kinds assemble into a graph consumed by the proxy
config plane and by service reads —

  service-router    L7 match rules → destinations (top of chain only,
                    compile.go:499 assembleChain)
  service-splitter  weighted traffic splits (compile.go:682)
  service-resolver  redirects, named subsets, default subset, per-subset
                    failover targets, connect timeout (compile.go:763)

plus ``service-defaults`` (protocol, external SNI) and
``proxy-defaults`` (global protocol fallback).

The compiled chain is a plain dict:

    {"service_name": str, "datacenter": str, "protocol": str,
     "start_node": node_key,
     "nodes":   {node_key: node_dict},
     "targets": {target_id: target_dict}}

Node keys are ``<type>:<name>``; target ids are
``<service>[:<subset>]@<dc>`` (our spelling of the reference's
``subset.service.namespace.dc`` DiscoveryTarget.ID — no namespaces in
this build, OSS semantics).

Behavioral parity pinned by tests/test_discoverychain.py against the
reference's compile_test.go golden cases: default chain, redirect,
circular-redirect error, default-subset, failover expansion, splitter
flattening, router catch-all route, L7-protocol gating, unknown-subset
and external-SNI validation errors.

Deviations (documented, deliberate): no namespaces/enterprise meta, no
hash-based load-balancer policy propagation, mesh-gateway mode is
recorded on targets but only ``default``/``remote``/``local`` strings
(no gateway endpoint rewriting here — that is the gateway locator's
job).
"""

from __future__ import annotations

from typing import Optional

DEFAULT_CONNECT_TIMEOUT_S = 5.0  # compile.go:848

# Protocols that permit routers/splitters (compile.go
# enableAdvancedRoutingForProtocol → structs.IsProtocolHTTPLike).
_L7_PROTOCOLS = ("http", "http2", "grpc")


class ChainCompileError(ValueError):
    """structs.ConfigEntryGraphError."""


def is_l7(protocol: str) -> bool:
    return protocol in _L7_PROTOCOLS


def target_id(service: str, subset: str, dc: str) -> str:
    return f"{service}:{subset}@{dc}" if subset else f"{service}@{dc}"


class _Compiler:
    """Single-use state for one compile (compile.go compiler struct)."""

    def __init__(self, service: str, datacenter: str, entries: dict,
                 use_in_datacenter: str, override_protocol: str,
                 override_connect_timeout_s: float):
        self.service = service
        self.dc = datacenter
        self.use_in_dc = use_in_datacenter or datacenter
        self.entries = entries or {}
        self.override_protocol = override_protocol
        self.override_connect_timeout_s = override_connect_timeout_s

        self.nodes: dict[str, dict] = {}
        self.targets: dict[str, dict] = {}
        self.retained: set[str] = set()
        self.protocol: str = ""
        self.uses_advanced = False
        self.start_node = ""
        # With an L4 override the chain must not include routers or
        # splitters (CompileRequest.OverrideProtocol contract).
        self.disable_advanced = bool(
            override_protocol and not is_l7(override_protocol)
        )

    # -- config-entry lookups ------------------------------------------

    def _resolver(self, service: str) -> dict:
        rec = (self.entries.get("resolvers") or {}).get(service)
        return rec if rec is not None else {"name": service, "default": True}

    def _splitter(self, service: str) -> Optional[dict]:
        if self.disable_advanced:
            return None
        return (self.entries.get("splitters") or {}).get(service)

    def _router(self, service: str) -> Optional[dict]:
        if self.disable_advanced:
            return None
        return (self.entries.get("routers") or {}).get(service)

    def _service_defaults(self, service: str) -> dict:
        return (self.entries.get("services") or {}).get(service) or {}

    def _global_proxy(self) -> dict:
        return self.entries.get("global_proxy") or {}

    # -- protocol discipline (compile.go:211-250) ----------------------

    def _record_protocol(self, service: str) -> None:
        proto = (
            self._service_defaults(service).get("protocol")
            or (self._global_proxy().get("config") or {}).get("protocol")
            or self._global_proxy().get("protocol")
            or "tcp"
        )
        if not self.protocol:
            self.protocol = proto
        elif self.protocol != proto:
            raise ChainCompileError(
                f"discovery chain {self.service!r} crosses services using "
                f"different protocols ({self.protocol!r} then {proto!r} at "
                f"{service!r}); change the upstream references or align "
                "the protocols"
            )

    # -- targets -------------------------------------------------------

    def _new_target(self, service: str, subset: str, dc: str) -> dict:
        tid = target_id(service, subset, dc or self.dc)
        if tid not in self.targets:
            self.targets[tid] = {
                "id": tid,
                "service": service,
                "subset": subset,
                "datacenter": dc or self.dc,
                "mesh_gateway": "default",
                "external": False,
                "sni": "",
            }
        return self.targets[tid]

    def _rewrite_target(self, t: dict, service: str, subset: str,
                        dc: str) -> dict:
        """compile.go:646 rewriteTarget: referencing another service
        resets the chosen subset."""
        svc, sub, d = t["service"], t["subset"], t["datacenter"]
        if service and service != svc:
            svc, sub = service, ""
        if subset:
            sub = subset
        if dc:
            d = dc
        return self._new_target(svc, sub, d)

    # -- graph assembly ------------------------------------------------

    def compile(self) -> dict:
        self._assemble()
        self._detect_cycles()
        self._flatten_adjacent_splitters()
        self._remove_unused()
        self.targets = {
            tid: t for tid, t in self.targets.items() if tid in self.retained
        }
        if self.uses_advanced and not is_l7(self.protocol):
            raise ChainCompileError(
                f"discovery chain {self.service!r} uses a protocol "
                f"{self.protocol!r} that does not permit advanced routing "
                "or splitting behavior"
            )
        if self.override_protocol:
            self.protocol = self.override_protocol
        return {
            "service_name": self.service,
            "datacenter": self.dc,
            "protocol": self.protocol,
            "start_node": self.start_node,
            "nodes": self.nodes,
            "targets": self.targets,
        }

    def _assemble(self) -> None:
        router = self._router(self.service)
        if router is None:
            node = self._splitter_or_resolver(
                self._new_target(self.service, "", ""))
            self.start_node = node["key"]
            return

        self._record_protocol(self.service)
        self.uses_advanced = True
        routes = []
        for route in router.get("routes", []):
            dest = route.get("destination") or {}
            svc = dest.get("service") or self.service
            subset = dest.get("service_subset", "")
            dc = dest.get("datacenter", "")
            if subset:
                nxt = self._resolver_node(
                    self._new_target(svc, subset, dc), for_failover=False)
            else:
                nxt = self._splitter_or_resolver(
                    self._new_target(svc, "", dc))
            routes.append({"definition": route, "next_node": nxt["key"]})
        # Catch-all route to the router's own service (compile.go:585).
        default_next = self._splitter_or_resolver(
            self._new_target(self.service, "", ""))
        routes.append({
            "definition": {"match": {"http": {"path_prefix": "/"}},
                           "destination": {"service": self.service}},
            "next_node": default_next["key"],
        })
        node = {"type": "router", "name": self.service,
                "key": f"router:{self.service}", "routes": routes}
        self.nodes[node["key"]] = node
        self.start_node = node["key"]

    def _splitter_or_resolver(self, target: dict) -> dict:
        node = self._splitter_node(target["service"])
        if node is not None:
            return node
        return self._resolver_node(target, for_failover=False)

    def _splitter_node(self, service: str) -> Optional[dict]:
        key = f"splitter:{service}"
        if key in self.nodes:
            return self.nodes[key]
        splitter = self._splitter(service)
        if splitter is None:
            return None
        self._record_protocol(service)
        node = {"type": "splitter", "name": service, "key": key,
                "splits": []}
        # Record before recursing so graph loops short-circuit
        # (compile.go:708).
        self.nodes[key] = node
        self.uses_advanced = True
        for split in splitter.get("splits", []):
            svc = split.get("service") or service
            subset = split.get("service_subset", "")
            if svc != service and not subset:
                nxt = self._splitter_node(svc)
                if nxt is not None:
                    node["splits"].append({"weight": split.get("weight", 0),
                                           "next_node": nxt["key"]})
                    continue
            res = self._resolver_node(
                self._new_target(svc, subset, ""), for_failover=False)
            node["splits"].append({"weight": split.get("weight", 0),
                                   "next_node": res["key"]})
        return node

    def _resolver_node(self, target: dict, for_failover: bool) -> dict:
        """compile.go:763 getResolverNode: redirects and default-subset
        rewrites loop back through resolution; failover recurses with
        for_failover=True to reuse that logic for target generation."""
        redirect_history: list[str] = []

        while True:
            key = f"resolver:{target['id']}"
            if key in self.nodes and not for_failover:
                return self.nodes[key]
            self._record_protocol(target["service"])
            resolver = self._resolver(target["service"])

            if target["id"] in redirect_history:
                chain = " -> ".join(redirect_history + [target["id"]])
                raise ChainCompileError(
                    f"detected circular resolver redirect: [{chain}]")
            redirect_history.append(target["id"])

            redirect = resolver.get("redirect")
            if redirect:
                nxt = self._rewrite_target(
                    target,
                    redirect.get("service", ""),
                    redirect.get("service_subset", ""),
                    redirect.get("datacenter", ""),
                )
                if nxt["id"] != target["id"]:
                    target = nxt
                    continue
            if not target["subset"] and resolver.get("default_subset"):
                target = self._rewrite_target(
                    target, "", resolver["default_subset"], "")
                continue
            break

        subsets = resolver.get("subsets") or {}
        if target["subset"] and target["subset"] not in subsets:
            raise ChainCompileError(
                f"service {target['service']!r} does not have a subset "
                f"named {target['subset']!r}")

        timeout = float(resolver.get("connect_timeout_s", 0) or 0)
        if timeout <= 0:
            timeout = DEFAULT_CONNECT_TIMEOUT_S
        if self.override_connect_timeout_s > 0:
            timeout = self.override_connect_timeout_s

        target["filter"] = (subsets.get(target["subset"]) or {}).get(
            "filter", "") if target["subset"] else ""
        target["only_passing"] = bool(
            (subsets.get(target["subset"]) or {}).get("only_passing", False)
        ) if target["subset"] else False

        defaults = self._service_defaults(target["service"])
        if defaults.get("external_sni"):
            target["sni"] = defaults["external_sni"]
            target["external"] = True
            for field, label in (("redirect", "redirects"),
                                 ("subsets", "subsets"),
                                 ("failover", "failover")):
                if resolver.get(field):
                    raise ChainCompileError(
                        f"service {target['service']!r} has an external SNI "
                        f"set; cannot define {label} for external services")

        # Mesh-gateway mode: per-service default, then proxy-defaults
        # (compile.go:905-930); local-dc targets need no gateway.
        if target["datacenter"] != self.use_in_dc and not target["external"]:
            mode = defaults.get("mesh_gateway") or \
                self._global_proxy().get("mesh_gateway") or "default"
            target["mesh_gateway"] = mode

        key = f"resolver:{target['id']}"
        node = {
            "type": "resolver", "name": target["id"], "key": key,
            "resolver": {
                "default": bool(resolver.get("default")),
                "target": target["id"],
                "connect_timeout_s": timeout,
                "failover": None,
            },
        }
        self.retained.add(target["id"])
        if for_failover:
            # Emitted for target generation only — not cached, and
            # failover does not nest (compile.go:934-940).
            return node
        self.nodes[key] = node

        failover_map = resolver.get("failover") or {}
        failover = failover_map.get(target["subset"] or "",
                                    failover_map.get("*"))
        if failover:
            fo_targets = []
            dcs = failover.get("datacenters") or [""]
            for dc in dcs:
                ft = self._rewrite_target(
                    target,
                    failover.get("service", ""),
                    failover.get("service_subset", ""),
                    dc,
                )
                if ft["id"] != target["id"]:  # don't fail over to yourself
                    fo_targets.append(ft)
            resolved = []
            for ft in fo_targets:
                fnode = self._resolver_node(ft, for_failover=True)
                resolved.append(fnode["resolver"]["target"])
            if resolved:
                node["resolver"]["failover"] = {"targets": resolved}
        return node

    # -- post passes (compile.go:333-497) ------------------------------

    def _detect_cycles(self) -> None:
        """compile.go:333 detectCircularReferences: a splitter graph
        loop (allowed to form by the record-before-recurse
        short-circuit) must fail the compile, not hang the flatten
        pass — this runs synchronously on the server event loop."""
        in_stack: list[str] = []
        done: set[str] = set()

        def edges(node: dict) -> list[str]:
            if node["type"] == "router":
                return [r["next_node"] for r in node["routes"]]
            if node["type"] == "splitter":
                return [s["next_node"] for s in node["splits"]]
            return []

        def visit(key: str) -> None:
            if key in in_stack:
                chain = " -> ".join(in_stack[in_stack.index(key):] + [key])
                raise ChainCompileError(
                    f"detected circular reference: [{chain}]")
            node = self.nodes.get(key)
            if node is None or key in done:
                return
            in_stack.append(key)
            for nxt in edges(node):
                visit(nxt)
            in_stack.pop()
            done.add(key)

        visit(self.start_node)

    def _flatten_adjacent_splitters(self) -> None:
        """splitter→splitter edges inline the child's splits, scaling
        weights (compile.go:388 flattenAdjacentSplitterNodes)."""
        changed = True
        while changed:
            changed = False
            for node in self.nodes.values():
                if node["type"] != "splitter":
                    continue
                flat = []
                for split in node["splits"]:
                    child = self.nodes.get(split["next_node"])
                    if child is not None and child["type"] == "splitter":
                        for sub in child["splits"]:
                            flat.append({
                                "weight": round(
                                    split["weight"] * sub["weight"] / 100.0,
                                    2),
                                "next_node": sub["next_node"],
                            })
                        changed = True
                    else:
                        flat.append(split)
                node["splits"] = flat

    def _remove_unused(self) -> None:
        seen: set[str] = set()
        frontier = [self.start_node]
        while frontier:
            key = frontier.pop()
            if key in seen or key not in self.nodes:
                continue
            seen.add(key)
            node = self.nodes[key]
            if node["type"] == "router":
                frontier += [r["next_node"] for r in node["routes"]]
            elif node["type"] == "splitter":
                frontier += [s["next_node"] for s in node["splits"]]
        self.nodes = {k: v for k, v in self.nodes.items() if k in seen}
        self.retained = {
            n["resolver"]["target"]
            for n in self.nodes.values() if n["type"] == "resolver"
        } | {
            t
            for n in self.nodes.values() if n["type"] == "resolver"
            and n["resolver"]["failover"]
            for t in n["resolver"]["failover"]["targets"]
        }


def compile_chain(service: str, datacenter: str, entries: dict,
                  use_in_datacenter: str = "",
                  override_protocol: str = "",
                  override_connect_timeout_s: float = 0.0) -> dict:
    """Assemble one service's discovery chain (compile.go:56 Compile).

    ``entries`` carries the relevant config entries, pre-indexed:
    ``{"resolvers": {name: entry}, "splitters": {...}, "routers": {...},
    "services": {name: service-defaults}, "global_proxy": proxy-defaults
    entry}`` — the shape ``entries_for_chain`` builds from the state
    store.
    """
    if not service:
        raise ChainCompileError("service name is required")
    return _Compiler(service, datacenter, entries, use_in_datacenter,
                     override_protocol, override_connect_timeout_s).compile()


def entries_for_chain(store, service: str, ws=None) -> tuple[int, dict]:
    """Gather the config entries a chain compile needs from the state
    store, in ONE table read that also registers the caller's watch
    (discoverychain/gateway.go ReadDiscoveryChainConfigEntries,
    collapsed: we read all entries of the relevant kinds — entry counts
    are small and the store read is index-consistent)."""
    out = {"resolvers": {}, "splitters": {}, "routers": {}, "services": {},
           "global_proxy": None}
    kind_slot = {"service-resolver": "resolvers",
                 "service-splitter": "splitters",
                 "service-router": "routers",
                 "service-defaults": "services"}
    idx, recs = store.config_entries_by_kind(None, ws=ws)
    for rec in recs:
        slot = kind_slot.get(rec.get("kind"))
        if slot is not None:
            out[slot][rec["name"]] = rec
        elif rec.get("kind") == "proxy-defaults":
            out["global_proxy"] = rec
    return idx, out
