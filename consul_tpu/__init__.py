"""consul-tpu: a TPU-native distributed-coordination framework.

A from-scratch re-design of HashiCorp Consul's capability set
(SWIM gossip membership + failure detection, Serf-style Lamport-clocked
event broadcast, Vivaldi network coordinates, Raft-backed catalog/KV with
blocking queries, HTTP/DNS/CLI surface) built JAX/XLA-first.

Its distinguishing capability is the *gossip simulation backend*: the
memberlist probe/suspect/dead state machine and Serf's user-event epidemic
broadcast are re-expressed as vectorized sparse message passing lowered to
``jax.lax.scan`` + scatter/segment ops, sharded with ``jax.sharding`` across
a TPU mesh, so failure-detection and broadcast-convergence behavior can be
studied at million-node scale.

Layout:
  - ``consul_tpu.protocol`` — protocol constants + scaling formulas
    (the ground truth both the simulator and the host agent obey).
  - ``consul_tpu.ops``      — array primitives (random peer sampling,
    infection scatter/arrival ops).
  - ``consul_tpu.models``   — the protocol planes as pure JAX models
    (SWIM failure detection, event broadcast).
  - ``consul_tpu.parallel`` — device-mesh / sharding helpers (node-axis
    sharding, segment<->device mapping).
  - ``consul_tpu.sim``      — scan-based simulation engine, metrics,
    and the baseline scenario presets.
"""

from consul_tpu.version import __version__

__all__ = ["__version__"]
