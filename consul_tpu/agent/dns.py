"""DNS interface: service discovery over the DNS protocol.

Equivalent of ``agent/dns.go`` (the miekg/dns server on :8600): node
lookups (``<node>.node.<dc>.consul``), service lookups
(``[<tag>.]<service>.service[.<dc>].consul``) with only-passing
filtering, RFC 2782 SRV names (``_svc._tag.service.consul``), prepared
query lookups (``<name>.query.consul``), SOA/NS, A/AAAA/SRV/TXT answer
synthesis, shuffled answers, and UDP truncation with the TC bit.

The wire codec is hand-rolled (RFC 1035 §4) — the image has no DNS
library.  Compression pointers are emitted for repeated names.
"""

from __future__ import annotations

import asyncio
import logging
import random
import struct
from typing import Optional

from consul_tpu.agent import cache
from consul_tpu.agent.agent import Agent

log = logging.getLogger("consul_tpu.dns")

# RR types/classes (RFC 1035 + 3596 + 6891).
TYPE_A = 1
TYPE_NS = 2
TYPE_SOA = 6
TYPE_PTR = 12
TYPE_TXT = 16
TYPE_AAAA = 28
TYPE_OPT = 41  # EDNS0 pseudo-RR (RFC 6891)
TYPE_SRV = 33
TYPE_ANY = 255
CLASS_IN = 1

RCODE_OK = 0
RCODE_SERVFAIL = 2
RCODE_NXDOMAIN = 3
RCODE_NOTIMPL = 4

UDP_PAYLOAD_MAX = 512    # pre-EDNS budget (dns.go truncation)
EDNS_PAYLOAD_MAX = 4096  # what we advertise back (dns.go setEDNS)
MAX_ANSWERS = 32  # dns.go a-record limit analogue
RECURSOR_TIMEOUT_S = 3.0  # dns.go recursor client timeout


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------


class DNSQuestion:
    def __init__(self, name: str, qtype: int, qclass: int):
        self.name = name
        self.qtype = qtype
        self.qclass = qclass


class DNSRecord:
    def __init__(self, name: str, rtype: int, ttl: int, rdata: bytes):
        self.name = name
        self.rtype = rtype
        self.ttl = ttl
        self.rdata = rdata


def _encode_name(name: str, offsets: dict[str, int], pos: int) -> bytes:
    """RFC 1035 name encoding with compression pointers."""
    labels = [l for l in name.rstrip(".").split(".") if l]
    out = b""
    for i in range(len(labels)):
        suffix = ".".join(labels[i:])
        if suffix in offsets:
            return out + struct.pack(">H", 0xC000 | offsets[suffix])
        if pos + len(out) < 0x3FFF:
            offsets[suffix] = pos + len(out)
        label = labels[i].encode()
        out += bytes([len(label)]) + label
    return out + b"\x00"


def _decode_name(buf: bytes, pos: int) -> tuple[str, int]:
    labels = []
    jumped = False
    end = pos
    hops = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated name")
        length = buf[pos]
        if length & 0xC0 == 0xC0:
            if hops > 32:
                raise ValueError("compression loop")
            hops += 1
            ptr = struct.unpack(">H", buf[pos:pos + 2])[0] & 0x3FFF
            if not jumped:
                end = pos + 2
            pos = ptr
            jumped = True
            continue
        pos += 1
        if length == 0:
            break
        labels.append(buf[pos:pos + length].decode(errors="replace"))
        pos += length
    if not jumped:
        end = pos
    return ".".join(labels), end


def parse_query(buf: bytes) -> tuple[int, list[DNSQuestion]]:
    txid, questions, _edns = parse_query_edns(buf)
    return txid, questions


def parse_query_edns(
    buf: bytes,
) -> tuple[int, list[DNSQuestion], Optional[int]]:
    """Decode (txid, questions, edns_payload).  ``edns_payload`` is the
    client's advertised UDP payload size from an OPT pseudo-RR in the
    additional section (RFC 6891 §6.2.3), or None without EDNS."""
    txid, flags, qd, an, ns, ar = struct.unpack(">HHHHHH", buf[:12])
    pos = 12
    questions = []
    for _ in range(qd):
        name, pos = _decode_name(buf, pos)
        qtype, qclass = struct.unpack(">HH", buf[pos:pos + 4])
        pos += 4
        questions.append(DNSQuestion(name, qtype, qclass))
    edns_payload: Optional[int] = None
    try:
        for _ in range(an + ns + ar):
            _, pos = _decode_name(buf, pos)
            rtype, rclass, _ttl, rdlen = struct.unpack(
                ">HHIH", buf[pos:pos + 10])
            pos += 10 + rdlen
            if rtype == TYPE_OPT:
                # For OPT the CLASS field carries the payload size.
                edns_payload = rclass
    except (ValueError, struct.error):
        pass  # malformed tail: serve the question without EDNS
    return txid, questions, edns_payload


def build_query(txid: int, name: str, qtype: int = TYPE_A) -> bytes:
    """Client-side query encoder (used by tests and the CLI resolver)."""
    header = struct.pack(">HHHHHH", txid, 0x0100, 1, 0, 0, 0)  # RD
    return header + _rd_name(name) + struct.pack(">HH", qtype, CLASS_IN)


def parse_response(buf: bytes) -> tuple[int, int, list[DNSRecord]]:
    """Decode (txid, rcode, answers) — rdata left raw."""
    txid, flags, qd, an, _ns, _ar = struct.unpack(">HHHHHH", buf[:12])
    pos = 12
    for _ in range(qd):
        _, pos = _decode_name(buf, pos)
        pos += 4
    answers = []
    for _ in range(an):
        name, pos = _decode_name(buf, pos)
        rtype, _rclass, ttl, rdlen = struct.unpack(">HHIH", buf[pos:pos + 10])
        pos += 10
        answers.append(DNSRecord(name, rtype, ttl, buf[pos:pos + rdlen]))
        pos += rdlen
    return txid, flags & 0xF, answers


def build_response(
    txid: int,
    questions: list[DNSQuestion],
    answers: list[DNSRecord],
    authority: list[DNSRecord],
    rcode: int,
    truncate_to: Optional[int] = UDP_PAYLOAD_MAX,
    edns: bool = False,
) -> bytes:
    flags = 0x8480 | (rcode & 0xF)  # QR|AA|RD-echo
    out = bytearray()
    offsets: dict[str, int] = {}
    # RFC 6891: when the client spoke EDNS we echo an OPT RR with our
    # own payload budget; reserve its 11 bytes from the truncation math.
    opt_rr = b"\x00" + struct.pack(
        ">HHIH", TYPE_OPT, EDNS_PAYLOAD_MAX, 0, 0) if edns else b""

    def emit_q(q: DNSQuestion) -> bytes:
        return _encode_name(q.name, offsets, 12 + len(out)) + struct.pack(
            ">HH", q.qtype, q.qclass
        )

    def emit_rr(r: DNSRecord) -> bytes:
        head = _encode_name(r.name, offsets, 12 + len(out))
        return head + struct.pack(
            ">HHIH", r.rtype, CLASS_IN, r.ttl, len(r.rdata)
        ) + r.rdata

    budget = (truncate_to - len(opt_rr)) if truncate_to else None
    for q in questions:
        out += emit_q(q)
    n_ans = 0
    truncated = False
    for r in answers:
        rr = emit_rr(r)
        if budget and 12 + len(out) + len(rr) > budget:
            truncated = True
            break
        out += rr
        n_ans += 1
    n_auth = 0
    if not truncated:
        for r in authority:
            rr = emit_rr(r)
            if budget and 12 + len(out) + len(rr) > budget:
                break
            out += rr
            n_auth += 1
    if truncated:
        flags |= 0x0200  # TC
    header = struct.pack(
        ">HHHHHH", txid, flags, len(questions), n_ans, n_auth,
        1 if edns else 0,
    )
    return header + bytes(out) + opt_rr


def _rd_a(ip: str) -> bytes:
    try:
        return bytes(int(p) for p in ip.split("."))
    except ValueError:
        return b"\x7f\x00\x00\x01"


def _rd_name(name: str) -> bytes:
    out = b""
    for label in name.rstrip(".").split("."):
        out += bytes([len(label)]) + label.encode()
    return out + b"\x00"


def _rd_srv(prio: int, weight: int, port: int, target: str) -> bytes:
    return struct.pack(">HHH", prio, weight, port) + _rd_name(target)


def _rd_txt(text: str) -> bytes:
    data = text.encode()[:255]
    return bytes([len(data)]) + data


# ---------------------------------------------------------------------------
# the server
# ---------------------------------------------------------------------------


def _split_host_port(addr: str, default_port: str = "53") -> tuple[str, str]:
    """IPv6-aware host:port split: "[::1]:53", "::1" (bare v6),
    "10.0.0.1:53", and "10.0.0.1" all parse correctly (the reference
    normalizes recursor addresses through net.SplitHostPort the same
    way, dns.go formatRecursorAddress)."""
    if addr.startswith("["):
        host, _, rest = addr[1:].partition("]")
        port = rest.lstrip(":") or default_port
        return host, port
    if addr.count(":") > 1:
        return addr, default_port  # bare IPv6, no port
    host, _, port = addr.rpartition(":")
    if not host:
        return addr, default_port
    return host, port or default_port


class DNSServer:
    """agent/dns.go DNSServer: dispatch on the .consul name space."""

    def __init__(self, agent: Agent, domain: str = "consul",
                 seed: int = 0):
        self.agent = agent
        self.domain = domain.strip(".").lower()
        self._rng = random.Random(seed)
        self._udp: Optional[asyncio.DatagramTransport] = None
        self._inflight: set[asyncio.Task] = set()
        self.addr = ""

    # DNS behavior follows the agent's live config knobs (dns_config
    # block; reloadable without restart — agent.go reloadConfigInternal).
    @property
    def node_ttl(self) -> int:
        return int(getattr(self.agent, "dns_node_ttl_s", 0.0))

    @property
    def only_passing(self) -> bool:
        return bool(getattr(self.agent, "dns_only_passing", True))

    @property
    def recursors(self) -> list[str]:
        """Upstream resolvers for non-.consul names (dns.go
        handleRecurse; config ``dns_config.recursors``)."""
        return list(getattr(self.agent, "dns_recursors", []) or [])

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        loop = asyncio.get_running_loop()
        server = self

        class Proto(asyncio.DatagramProtocol):
            def connection_made(self, transport):
                self.transport = transport

            def datagram_received(self, data, addr):
                # Hold a strong reference until done, or the loop's weak
                # ref lets the in-flight resolution be GC'd mid-query.
                task = asyncio.ensure_future(
                    server._handle(self.transport, data, addr)
                )
                server._inflight.add(task)
                task.add_done_callback(server._inflight.discard)

        self._udp, _ = await loop.create_datagram_endpoint(
            Proto, local_addr=(host, port)
        )
        h, p = self._udp.get_extra_info("sockname")[:2]
        self.addr = f"{h}:{p}"
        return self.addr

    async def stop(self) -> None:
        if self._udp:
            self._udp.close()

    async def _handle(self, transport, data: bytes, addr) -> None:
        try:
            txid, questions, edns_payload = parse_query_edns(data)
        except (ValueError, struct.error):
            return
        try:
            resp = await self.answer(txid, questions,
                                     edns_payload=edns_payload,
                                     raw_query=data)
        except Exception:  # noqa: BLE001
            log.exception("dns handler failed")
            resp = build_response(txid, questions, [], [], RCODE_NOTIMPL)
        transport.sendto(resp, addr)

    # -- resolution (dns.go:427 handleQuery → dispatch) -----------------

    async def answer(self, txid: int, questions: list[DNSQuestion],
                     edns_payload: Optional[int] = None,
                     raw_query: Optional[bytes] = None) -> bytes:
        edns = edns_payload is not None
        # RFC 6891 payload negotiation replaces the fixed 512 B budget
        # (dns.go setEDNS / truncation math).
        budget = UDP_PAYLOAD_MAX
        if edns:
            budget = max(UDP_PAYLOAD_MAX,
                         min(int(edns_payload), EDNS_PAYLOAD_MAX))

        def respond(answers, authority, rcode):
            return build_response(txid, questions, answers, authority,
                                  rcode, truncate_to=budget, edns=edns)

        if not questions:
            return respond([], [], RCODE_NXDOMAIN)
        q = questions[0]
        name = q.name.lower().rstrip(".")
        labels = name.split(".")
        domain_labels = self.domain.split(".")
        # Label-boundary match: "web.service.notconsul" and
        # "anythingconsul" are NOT ours (dns.go trimDomain).
        if labels[-len(domain_labels):] != domain_labels:
            # dns.go registers "arpa." for reverse lookups and "." for
            # recursor forwarding.
            if labels[-1] == "arpa":
                try:
                    answers = await self._ptr_lookup(labels, q)
                except LookupError:
                    answers = []
                if answers:
                    return respond(answers, [], RCODE_OK)
                if self.recursors and raw_query is not None:
                    return await self._recurse(txid, questions, raw_query)
                return respond([], [self._soa()], RCODE_NXDOMAIN)
            if self.recursors and raw_query is not None:
                return await self._recurse(txid, questions, raw_query)
            return respond([], [], RCODE_NXDOMAIN)
        core = labels[: -len(domain_labels)]
        answers: list[DNSRecord] = []
        rcode = RCODE_OK

        try:
            if not core or core == [""]:
                answers = [self._soa()]
            elif core[-1] == "node" or (len(core) >= 2 and core[-2] == "node"):
                answers = await self._node_lookup(core, q)
            elif "service" in core:
                answers = await self._service_lookup(core, q)
            elif core[-1] == "query":
                answers = await self._query_lookup(core, q)
            else:
                rcode = RCODE_NXDOMAIN
        except LookupError:
            rcode = RCODE_NXDOMAIN

        if not answers and rcode == RCODE_OK:
            rcode = RCODE_NXDOMAIN
        authority = [] if answers else [self._soa()]
        return respond(answers, authority, rcode)

    async def _ptr_lookup(self, labels: list[str],
                          q: DNSQuestion) -> list[DNSRecord]:
        """Reverse lookups over the node address index
        (dns.go:199 registers ``arpa.`` → handlePtr at :324): the
        in-addr.arpa octets reverse into an IPv4 address, matched
        against catalog node addresses; service addresses answer with
        their service name."""
        if labels[-2:] != ["in-addr", "arpa"] or len(labels) < 3:
            raise LookupError(".".join(labels))
        ip = ".".join(reversed(labels[:-2]))
        out = await self.agent.cached_rpc(
            cache.CATALOG_LIST_NODES, {"allow_stale": True}
        )
        recs = []
        for node in out.get("nodes") or []:
            if node.get("address") == ip:
                target = f"{node['node']}.node.{self.domain}."
                recs.append(DNSRecord(q.name, TYPE_PTR, self.node_ttl,
                                      _rd_name(target)))
        if not recs:
            # handlePtr also answers for service addresses
            # (dns.go:376-393 checkServiceNodes by ServiceAddress).
            svc_out = await self.agent.cached_rpc(
                cache.CATALOG_SERVICES_DUMP, {"allow_stale": True}
            )
            for svc in svc_out.get("services") or []:
                if svc.get("address") == ip:
                    target = (f"{svc['service']}.service."
                              f"{self.domain}.")
                    recs.append(DNSRecord(
                        q.name, TYPE_PTR, self.node_ttl,
                        _rd_name(target)))
        if not recs:
            raise LookupError(ip)
        return recs

    async def _recurse(self, txid: int, questions: list[DNSQuestion],
                       raw_query: bytes) -> bytes:
        """Forward the raw query to the configured recursors in order
        (dns.go handleRecurse): first response wins, SERVFAIL when all
        fail."""
        loop = asyncio.get_running_loop()
        for recursor in self.recursors:
            host, port = _split_host_port(recursor)
            try:
                reply_fut: asyncio.Future = loop.create_future()

                class _Client(asyncio.DatagramProtocol):
                    def connection_made(self, transport):
                        transport.sendto(raw_query)

                    def datagram_received(self, data, _addr):
                        if not reply_fut.done():
                            reply_fut.set_result(data)

                    def error_received(self, exc):
                        if not reply_fut.done():
                            reply_fut.set_exception(exc)

                transport, _ = await loop.create_datagram_endpoint(
                    _Client, remote_addr=(host, int(port))
                )
                try:
                    return await asyncio.wait_for(
                        reply_fut, RECURSOR_TIMEOUT_S)
                finally:
                    transport.close()
            except (OSError, asyncio.TimeoutError, ValueError) as e:
                log.warning("recursor %s failed: %s", recursor, e)
        return build_response(txid, questions, [], [], RCODE_SERVFAIL)

    def _soa(self) -> DNSRecord:
        """dns.go soa(): ns.<domain> authority record."""
        rdata = (
            _rd_name(f"ns.{self.domain}")
            + _rd_name(f"hostmaster.{self.domain}")
            + struct.pack(">IIIII", 1, 3600, 600, 86400, 0)
        )
        return DNSRecord(self.domain, TYPE_SOA, 0, rdata)

    async def _node_lookup(self, core: list[str], q: DNSQuestion) -> list[DNSRecord]:
        """<node>.node[.<dc>].consul (dns.go nodeLookup)."""
        idx = core.index("node") if "node" in core else len(core) - 1
        node = ".".join(core[:idx])
        out = await self.agent.cached_rpc(
            cache.NODE_INFO, {"node": node, "allow_stale": True}
        )
        dump = out.get("dump") or []
        if not dump:
            raise LookupError(node)
        addr = dump[0]["node"].get("address", "")
        recs = [DNSRecord(q.name, TYPE_A, self.node_ttl, _rd_a(addr))]
        if q.qtype == TYPE_TXT:
            meta = dump[0]["node"].get("meta", {})
            recs = [
                DNSRecord(q.name, TYPE_TXT, self.node_ttl,
                          _rd_txt(f"{k}={v}"))
                for k, v in meta.items()
            ] or [DNSRecord(q.name, TYPE_TXT, self.node_ttl, _rd_txt(""))]
        return recs

    async def _service_lookup(self, core: list[str], q: DNSQuestion) -> list[DNSRecord]:
        """[<tag>.]<service>.service[.<dc>] and RFC 2782
        _<service>._<proto> forms (dns.go serviceLookup)."""
        svc_idx = core.index("service")
        head = core[:svc_idx]
        tag = None
        if len(head) == 1:
            service = head[0]
        elif len(head) == 2:
            tag, service = head[0], head[1]
        else:
            raise LookupError(".".join(core))
        if service.startswith("_"):  # RFC 2782: _svc._tag
            service = service[1:]
            if tag and tag.startswith("_"):
                tag = tag[1:]
        # RFC 2782 ordering puts service first: _web._tcp → head is
        # [_web, _tcp] so swap after underscore stripping.
        if tag and head[0].startswith("_"):
            service, tag = head[0][1:], head[1].lstrip("_")
            if tag == "tcp" or tag == "udp":
                tag = None
        body = {"service": service, "allow_stale": True,
                "passing_only": self.only_passing}
        if tag:
            body["tag"] = tag
        out = await self.agent.cached_rpc(cache.HEALTH_SERVICES, body)
        # Cached values are shared: copy before shuffling.
        rows = list(out.get("nodes") or [])
        if not rows:
            raise LookupError(service)
        self._rng.shuffle(rows)
        rows = rows[:MAX_ANSWERS]
        recs = []
        for row in rows:
            svc = row["service"]
            ip = svc.get("address") or svc.get("node_address") or ""
            if q.qtype == TYPE_SRV:
                target = f"{svc['node']}.node.{self.domain}."
                recs.append(DNSRecord(
                    q.name, TYPE_SRV, self.node_ttl,
                    _rd_srv(1, 1, int(svc.get("port", 0)), target),
                ))
                recs.append(DNSRecord(
                    target.rstrip("."), TYPE_A, self.node_ttl, _rd_a(ip)
                ))
            else:
                recs.append(DNSRecord(q.name, TYPE_A, self.node_ttl,
                                      _rd_a(ip)))
        return recs

    async def _query_lookup(self, core: list[str], q: DNSQuestion) -> list[DNSRecord]:
        """<name-or-id>.query.consul (dns.go preparedQueryLookup)."""
        name = ".".join(core[:-1])
        out = await self.agent.cached_rpc(
            cache.PREPARED_QUERY, {"query_id": name, "allow_stale": True}
        )
        if out.get("error"):
            raise LookupError(name)
        rows = list(out.get("nodes") or [])
        if not rows:
            raise LookupError(name)
        self._rng.shuffle(rows)
        recs = []
        for row in rows[:MAX_ANSWERS]:
            svc = row["service"]
            ip = svc.get("address") or svc.get("node_address") or ""
            if q.qtype == TYPE_SRV:
                target = f"{svc['node']}.node.{self.domain}."
                recs.append(DNSRecord(
                    q.name, TYPE_SRV, self.node_ttl,
                    _rd_srv(1, 1, int(svc.get("port", 0)), target),
                ))
                recs.append(DNSRecord(
                    target.rstrip("."), TYPE_A, self.node_ttl, _rd_a(ip)
                ))
            else:
                recs.append(DNSRecord(q.name, TYPE_A, self.node_ttl,
                                      _rd_a(ip)))
        return recs
