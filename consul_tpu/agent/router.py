"""Cross-datacenter routing over the WAN gossip pool.

The reference's router (agent/router/router.go:22-137) tracks "areas" —
serf pools — and the servers discovered in each, keyed by datacenter;
its headline query is GetDatacentersByDistance (router.go:534), which
orders DCs by median Vivaldi round-trip estimate from the local node so
prepared-query failover and cross-DC work walk the nearest DCs first.

Here the single WAN area is the server's WAN serf pool: members are
named ``<node>.<dc>`` and carry dc/rpc_addr tags
(agent/consul/server_serf.go:35-120 tags); coordinates come from the
pool's ping piggyback (consul_tpu/net/vivaldi.py).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional

from consul_tpu.eventing.cluster import Cluster, MemberStatus


@dataclasses.dataclass
class ServerMeta:
    """router/manager.go metadata for one discovered server."""

    name: str         # WAN name, "<node>.<dc>"
    node: str         # bare node name
    dc: str
    rpc_addr: str


class Router:
    """Datacenter → servers map + RTT-ordered DC selection."""

    def __init__(self, local_dc: str, wan: Optional[Cluster]):
        self.local_dc = local_dc
        self.wan = wan
        self._rng = random.Random(hash(local_dc) & 0xFFFFFFFF)

    # ------------------------------------------------------------------
    # membership view (router.go:153-230 addServer/removeServer via the
    # serf adapter — here computed from the live WAN member list)
    # ------------------------------------------------------------------

    def servers_by_dc(self) -> dict[str, list[ServerMeta]]:
        out: dict[str, list[ServerMeta]] = {}
        if self.wan is None:
            return out
        for m in self.wan.members.values():
            if m.status != MemberStatus.ALIVE:
                continue
            dc = m.tags.get("dc")
            rpc = m.tags.get("rpc_addr")
            if not dc or not rpc:
                continue
            node = m.tags.get("id") or m.name.rsplit(".", 1)[0]
            out.setdefault(dc, []).append(
                ServerMeta(name=m.name, node=node, dc=dc, rpc_addr=rpc)
            )
        return out

    def servers_in_dc(self, dc: str) -> list[ServerMeta]:
        servers = self.servers_by_dc().get(dc, [])
        self._rng.shuffle(servers)
        return servers

    def datacenters(self) -> list[str]:
        return sorted(self.servers_by_dc())

    # ------------------------------------------------------------------
    # distance ordering (router.go:534 GetDatacentersByDistance)
    # ------------------------------------------------------------------

    def get_datacenters_by_distance(self) -> list[str]:
        """DCs ordered by median RTT estimate from us; the local DC
        always first; DCs with no usable coordinates sort last,
        alphabetically (router.go:534-607 sorts with infinite distance
        for missing coordinates)."""
        by_dc = self.servers_by_dc()
        if self.local_dc not in by_dc:
            by_dc.setdefault(self.local_dc, [])
        me = self.wan.get_coordinate() if self.wan else None

        def median_rtt(dc: str) -> float:
            if dc == self.local_dc:
                return -1.0
            if me is None or self.wan is None:
                return float("inf")
            dists = []
            for s in by_dc.get(dc, ()):
                coord = self.wan.get_cached_coordinate(s.name)
                if coord is not None:
                    dists.append(me.distance_to(coord))
            if not dists:
                return float("inf")
            dists.sort()
            return dists[len(dists) // 2]

        return sorted(by_dc, key=lambda dc: (median_rtt(dc), dc))
