"""The Agent: composition root tying the delegate, local state, checks
and user events together.

Equivalent of ``agent/agent.go`` (SURVEY.md §2.3): every node runs an
Agent; 3-5 run with a Server delegate (raft quorum), the rest with a
Client delegate.  The agent owns

  delegate            agent.go:121-123,167 — ``*consul.Server`` or
                      ``*consul.Client`` behind one RPC interface
  local state + AE    agent/local + agent/ae — the agent's services/
                      checks, anti-entropy synced into the catalog
  check executors     agent/checks — TTL/script/TCP/HTTP runners
                      feeding local state
  user events         agent/user_event.go:78-139 — serf events with a
                      dedup ring, exposed to the API/watches
  coordinate publish  agent keeps the server's Vivaldi coordinate
                      fresh via Coordinate.Update (ping piggyback in
                      the reference; a periodic task here)
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import uuid
from typing import Callable, Optional, Union

from consul_tpu.agent.checks import CheckRunner, CheckTTL, build_check_runner
from consul_tpu.agent.client import Client, ClientConfig
from consul_tpu.agent.local import LocalState, StateSyncer
from consul_tpu.agent.server import Server, ServerConfig
from consul_tpu.eventing.cluster import Event, EventType
from consul_tpu.net.transport import Transport
from consul_tpu.protocol import LAN, GossipProfile

log = logging.getLogger("consul_tpu.agent")

USER_EVENT_BUFFER = 256  # user_event.go agent-side ring


@dataclasses.dataclass
class AgentConfig:
    node_name: str
    datacenter: str = "dc1"
    server: bool = True
    bootstrap_expect: int = 1
    profile: GossipProfile = LAN
    gossip_interval_scale: float = 1.0
    advertise_addr: str = ""
    sync_interval_s: float = 60.0
    sync_retry_interval_s: float = 15.0  # ae.go retryFailIntv
    # Test-speed knobs forwarded to the Server delegate.
    reconcile_interval_s: float = 60.0
    coordinate_update_period_s: float = 5.0
    session_ttl_sweep_s: float = 1.0
    # ACLs (forwarded to ServerConfig).
    acl_enabled: bool = False
    acl_default_policy: str = "allow"
    acl_master_token: str = ""
    # Token the agent itself uses for anti-entropy catalog writes
    # (agent/config acl.tokens.agent).
    acl_agent_token: str = ""
    # Serf gossip snapshot + auto-rejoin (serf/snapshot.go).
    serf_snapshot_path: str = ""
    rejoin_after_leave: bool = False
    # Gossip encryption key, base64 (config "encrypt"; consul keygen).
    encrypt_key: str = ""
    # WAN replication (forwarded to ServerConfig).
    primary_datacenter: str = ""
    acl_replication_token: str = ""
    # Client TLS bootstrap (agent/auto-config + auto_encrypt_endpoint):
    # fetch an agent-kind SPIFFE leaf + CA roots from the servers at
    # startup.
    auto_encrypt: bool = False
    # Network segment membership for CLIENT agents (types/area.go /
    # agent config "segment"): the client's gossip ring name — join
    # addresses must point at a server's matching segment transport.
    segment: str = ""
    # Full auto-config bootstrap (agent/auto-config/config.go +
    # consul/auto_config_endpoint.go): a CLIENT with only a server RPC
    # address and a JWT intro token fetches its whole runtime (gossip
    # keys, agent token, TLS identity, cluster settings) before joining.
    auto_config_enabled: bool = False
    auto_config_intro_token: str = ""
    auto_config_server_addresses: tuple = ()
    # Server side: the JWT authorizer spec (ServerConfig field).
    auto_config_authorizer: Optional[dict] = None


@dataclasses.dataclass
class UserEvent:
    id: str
    name: str
    payload: bytes
    ltime: int


class Agent:
    """One Consul agent (``agent.Agent``)."""

    def __init__(
        self,
        config: AgentConfig,
        gossip_transport: Transport,
        rpc_transport: Optional[Transport] = None,
        wan_transport: Optional[Transport] = None,
    ):
        self.config = config
        # Shared keyring for LAN (and WAN) gossip (security.go).
        self.keyring = None
        if config.encrypt_key:
            from consul_tpu.net.security import Keyring

            self.keyring = Keyring.from_b64(config.encrypt_key)
        if config.server:
            if rpc_transport is None:
                raise ValueError("server agents need an rpc transport")
            self.delegate: Union[Server, Client] = Server(
                ServerConfig(
                    node_name=config.node_name,
                    datacenter=config.datacenter,
                    bootstrap_expect=config.bootstrap_expect,
                    profile=config.profile,
                    gossip_interval_scale=config.gossip_interval_scale,
                    reconcile_interval_s=config.reconcile_interval_s,
                    coordinate_update_period_s=config.coordinate_update_period_s,
                    session_ttl_sweep_s=config.session_ttl_sweep_s,
                    acl_enabled=config.acl_enabled,
                    acl_default_policy=config.acl_default_policy,
                    acl_master_token=config.acl_master_token,
                    serf_snapshot_path=config.serf_snapshot_path,
                    rejoin_after_leave=config.rejoin_after_leave,
                    keyring=self.keyring,
                    primary_datacenter=config.primary_datacenter,
                    acl_replication_token=config.acl_replication_token,
                    auto_config_authorizer=config.auto_config_authorizer,
                ),
                gossip_transport,
                rpc_transport,
                wan_transport=wan_transport,
            )
        elif wan_transport is not None:
            raise ValueError("only server agents join the WAN pool")
        else:
            if rpc_transport is None:
                raise ValueError("client agents need an rpc transport")
            self.delegate = Client(
                ClientConfig(
                    node_name=config.node_name,
                    datacenter=config.datacenter,
                    profile=config.profile,
                    gossip_interval_scale=config.gossip_interval_scale,
                    keyring=self.keyring,
                    tags=(
                        {"segment": config.segment}
                        if config.segment else {}
                    ),
                ),
                gossip_transport,
                rpc_transport,
            )

        addr = config.advertise_addr or gossip_transport.local_addr()
        self.local = LocalState(config.node_name, self._agent_rpc, address=addr)
        self.syncer = StateSyncer(
            self.local,
            cluster_size=lambda: len(self.serf.members) or 1,
            sync_interval_s=config.sync_interval_s,
            retry_interval_s=config.sync_retry_interval_s,
        )
        # Agent cache: typed, background-blocking-refresh reads
        # (agent/cache, cache.go:285/488/717), primarily feeding DNS.
        from consul_tpu.agent.cache import AgentCache

        # Reads through the cache run as the AGENT identity so DNS
        # works under ACL enforcement (the reference's DNS RPCs carry
        # the agent token too).
        self.cache = AgentCache(rpc=self._agent_rpc)
        # Proxy config snapshots for registered connect-proxy services
        # (agent/proxycfg/manager.go; wired in add/remove_service).
        from consul_tpu.connect.proxycfg import ProxyConfigManager

        self.proxycfg = ProxyConfigManager(
            self.cache, self._agent_rpc, datacenter=config.datacenter
        )
        self.checks: dict[str, CheckRunner] = {}
        # DNS behavior knobs (dns_config block); DNSServer reads these
        # live, so reload changes DNS behavior without a restart.
        self.dns_only_passing = True
        self.dns_node_ttl_s = 0.0
        self.dns_recursors: list[str] = []
        # Config-file-sourced definitions (loadServices/loadChecks),
        # swapped wholesale on reload.
        self._config_services: list[dict] = []
        self._config_checks: list[dict] = []
        self._config_service_ids: set[str] = set()
        self._config_check_ids: set[str] = set()
        self.tls_identity = None  # auto-encrypt result (leaf + roots)
        self.events: list[UserEvent] = []  # dedup ring, newest last
        self.event_index = 0  # monotonic, the X-Consul-Index for /event/list
        self._event_seen: set[tuple[int, str]] = set()
        self.event_handlers: list[Callable[[UserEvent], None]] = []
        self._event_wake = asyncio.Event()

        # Chain onto the serf event stream without stealing the
        # delegate's own handler (server reconcile wake).
        serf_cfg = self.serf.config
        prev = serf_cfg.on_event

        def chained(event: Event) -> None:
            if prev is not None:
                prev(event)
            self._on_serf_event(event)

        serf_cfg.on_event = chained

    # ------------------------------------------------------------------

    @property
    def serf(self):
        return self.delegate.serf

    def is_server(self) -> bool:
        return isinstance(self.delegate, Server)

    async def rpc(self, method: str, body: dict):
        """The one RPC entry point (agent.go:1296 a.RPC): servers
        execute locally, clients forward (SURVEY.md §3.4)."""
        if isinstance(self.delegate, Server):
            return await self.delegate.rpc_server.dispatch_local(method, body)
        return await self.delegate.rpc(method, body)

    async def _agent_rpc(self, method: str, body: dict):
        """RPC as the AGENT identity: anti-entropy writes carry the
        agent token (agent/config acl.tokens.agent) so registration
        sync works under ACL enforcement."""
        if self.config.acl_agent_token and "token" not in body:
            body = {**body, "token": self.config.acl_agent_token}
        return await self.rpc(method, body)

    async def keyring_operation(self, op: str, key_b64: str = "") -> dict:
        """operator keyring (operator_endpoint.go KeyringOperation):
        fan the op over the LAN pool (and the WAN pool on servers)."""
        pools = [("lan", self.serf)]
        wan = getattr(self.delegate, "serf_wan", None)
        if wan is not None:
            pools.append(("wan", wan))
        out = {}
        for label, pool in pools:
            fn = getattr(pool, op.replace("-", "_"))
            out[label] = await (fn(key_b64) if key_b64 else fn())
        return out

    async def cached_rpc(self, cache_type: str, body: dict):
        """Read through the agent cache (agent.go cache-backed RPCs with
        QueryOptions.UseCache): warm entries answer instantly while a
        background blocking query keeps them fresh."""
        return await self.cache.get(cache_type, body)

    async def start(self) -> None:
        if self.config.auto_config_enabled and not self.is_server():
            # agent/auto-config/auto_config.go InitialConfiguration:
            # runs BEFORE gossip starts — the response carries the
            # gossip encryption keys the join itself needs.
            await self._auto_config_bootstrap()
        await self.delegate.start()
        self.syncer.start()
        # TLS identity: servers mint theirs locally; clients ask the
        # servers (auto-encrypt).  Stored as self.tls_identity =
        # {"leaf": {...}, "roots": [...]} for transports/proxies to use.
        if self.config.auto_encrypt and not self.is_server():
            self._auto_encrypt_task = asyncio.create_task(
                self._auto_encrypt_loop()
            )

    async def _auto_config_bootstrap(self) -> None:
        """Fetch and APPLY the initial configuration from a configured
        server address, retrying across addresses with backoff (the
        reference persists the response; here it is applied live)."""
        from consul_tpu.agent.rpc import RPCError

        addrs = list(self.config.auto_config_server_addresses)
        if not addrs:
            raise ValueError(
                "auto_config requires auto_config_server_addresses"
            )
        backoff = 0.2
        while True:
            last: Exception = RPCError("no auto-config server reachable")
            for addr in addrs:
                try:
                    out = await self.delegate.rpc_client.call(
                        addr, "AutoConfig.InitialConfiguration",
                        {"node": self.config.node_name,
                         "jwt": self.config.auto_config_intro_token},
                    )
                    self._apply_auto_config(out)
                    return
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — next addr/retry
                    # A denial from ANY address means the intro token is
                    # bad — that never heals by retrying (a later
                    # unreachable address must not mask it).
                    if isinstance(e, RPCError) and \
                            "Permission denied" in str(e):
                        raise
                    last = e
            log.warning("auto-config bootstrap failed (%s); retrying", last)
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 5.0)

    def _apply_auto_config(self, out: dict) -> None:
        cfg = out.get("config") or {}
        # Gossip encryption: install the keys into the delegate's
        # memberlist config before serf starts.
        keys = out.get("gossip_keys") or []
        if keys:
            from consul_tpu.net.security import Keyring

            keyring = Keyring.from_b64(keys[0])
            for extra in keys[1:]:
                keyring.install(extra)
            self.keyring = keyring
            self.delegate.serf.memberlist.config.keyring = keyring
        # ACL agent token for anti-entropy + agent-plane RPCs.
        token = ((cfg.get("acl") or {}).get("tokens") or {}).get("agent")
        if token:
            self.config.acl_agent_token = token
        # TLS identity (the auto-encrypt shape).
        if out.get("tls"):
            self.tls_identity = out["tls"]
        # Datacenter: the delegate, its serf 'dc' tag, and the server
        # manager were all constructed with the pre-bootstrap value —
        # re-point ALL of them (a dc applied only to AgentConfig would
        # leave ServerManager filtering on the wrong tag and the client
        # unable to find any server).
        dc = cfg.get("datacenter", self.config.datacenter)
        if dc != self.config.datacenter:
            self.config.datacenter = dc
            self.delegate.config.datacenter = dc
            self.delegate.routers.datacenter = dc
            self.delegate.serf.config.tags["dc"] = dc
        self.config.primary_datacenter = cfg.get(
            "primary_datacenter", self.config.primary_datacenter)
        log.info(
            "auto-config: applied initial configuration "
            "(%d gossip key(s), token=%s, tls=%s)",
            len(keys), "yes" if token else "no",
            "yes" if out.get("tls") else "no",
        )

    async def _auto_encrypt_loop(self) -> None:
        """Fetch, then RENEW: retry with backoff until the servers
        answer (a fresh client may join before a leader exists), and
        re-sign at half the leaf's remaining lifetime so expiry and CA
        rotation never strand a stale identity (auto_encrypt.go renews
        at a fraction of the TTL)."""
        backoff = 0.2
        while True:
            try:
                # As the AGENT identity: Sign requires node:write on our
                # own name under ACL enforcement (auto_encrypt uses the
                # configured tokens.agent, like anti-entropy writes).
                out = await self._agent_rpc(
                    "AutoEncrypt.Sign", {"node": self.config.node_name}
                )
                self.tls_identity = out
                log.info("auto-encrypt: TLS identity issued (%s)",
                         out["leaf"]["uri"])
                backoff = 0.2
                import datetime

                expires = datetime.datetime.fromisoformat(
                    out["leaf"]["valid_before"]
                )
                remaining = (
                    expires - datetime.datetime.now(datetime.timezone.utc)
                ).total_seconds()
                await asyncio.sleep(max(remaining / 2, 60.0))
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - keep retrying
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 5.0)

    async def join(self, addrs: list[str]) -> int:
        return await self.delegate.join(addrs)

    async def leave(self) -> None:
        await self.delegate.leave()

    async def force_leave(self, node: str) -> bool:
        """agent.go ForceLeave -> serf.RemoveFailedNode."""
        return await self.serf.remove_failed_node(node)

    async def shutdown(self) -> None:
        self.syncer.stop()
        self.proxycfg.stop()
        self.cache.stop()
        task = getattr(self, "_auto_encrypt_task", None)
        if task is not None:
            task.cancel()
        for runner in self.checks.values():
            runner.stop()
        await self.delegate.shutdown()

    # ------------------------------------------------------------------
    # config-sourced definitions + reload (agent.go loadServices /
    # loadChecks / reloadConfigInternal)
    # ------------------------------------------------------------------

    def load_definitions(self, services: list[dict],
                         checks: list[dict]) -> None:
        """(Re)apply config-file service/check definitions: definitions
        no longer present are deregistered, the rest re-registered —
        the reload path changes checks without an agent restart."""
        self._config_services = [dict(s) for s in services]
        self._config_checks = [dict(c) for c in checks]
        new_svc_ids = set()
        for svc in services:
            svc = dict(svc)
            svc.setdefault("service", svc.pop("name", ""))
            sid = svc.get("id") or svc["service"]
            svc["id"] = sid
            new_svc_ids.add(sid)
            svc_checks = [dict(c) for c in svc.pop("checks", [])]
            self.add_service(svc, svc_checks)
        new_check_ids = set()
        for chk in checks:
            chk = dict(chk)
            cid = chk.get("check_id") or chk.get("id") or chk.get("name", "")
            chk["check_id"] = cid  # add_check registers under check_id
            new_check_ids.add(cid)
            self.add_check(chk)
        for sid in self._config_service_ids - new_svc_ids:
            self.remove_service(sid)
        for cid in self._config_check_ids - new_check_ids:
            self.remove_check(cid)
        self._config_service_ids = new_svc_ids
        self._config_check_ids = new_check_ids

    def reload(self, apply: dict) -> None:
        """Apply a reloadable-config diff (see config.reloadable_diff):
        service/check definitions swap in place (a field absent from the
        diff keeps its current definitions); scalar knobs update."""
        from consul_tpu.agent.config import thaw

        if "services" in apply or "checks" in apply:
            services = (
                [thaw(s) for s in apply["services"]]
                if "services" in apply
                else self._config_services
            )
            checks = (
                [thaw(c) for c in apply["checks"]]
                if "checks" in apply
                else self._config_checks
            )
            self.load_definitions(services, checks)
        for knob in ("dns_only_passing", "dns_node_ttl_s",
                     "dns_recursors"):
            if knob in apply:
                value = apply[knob]
                if knob == "dns_recursors":
                    value = list(value)
                setattr(self, knob, value)

    # ------------------------------------------------------------------
    # service & check registration (agent.go AddService/AddCheck)
    # ------------------------------------------------------------------

    def add_service(self, service: dict, checks: Optional[list[dict]] = None) -> None:
        sid = service.get("id") or service["service"]
        self.local.add_service(service)
        self.proxycfg.register(dict(service, id=sid))
        for i, defn in enumerate(checks or []):
            defn = dict(defn)
            defn.setdefault("check_id", f"service:{sid}" + (f":{i+1}" if i else ""))
            defn["service_id"] = sid
            defn.setdefault("service_name", service["service"])
            self.add_check(defn)

    def remove_service(self, service_id: str) -> bool:
        self.proxycfg.deregister(service_id)
        for cid, runner in list(self.checks.items()):
            entry = self.local.checks.get(cid)
            if entry and entry.check.get("service_id") == service_id:
                runner.stop()
                del self.checks[cid]
        return self.local.remove_service(service_id)

    # -- maintenance mode (agent.go:3411-3483 EnableServiceMaintenance /
    # EnableNodeMaintenance): a synthetic CRITICAL check pulls the
    # service (or every service on the node) out of discovery until
    # disabled; the reason lands in the check notes.

    def enable_service_maintenance(self, service_id: str,
                                   reason: str = "") -> bool:
        entry = self.local.services.get(service_id)
        if entry is None or entry.deleted:
            return False
        self.local.add_check({
            "check_id": f"_service_maintenance:{service_id}",
            "name": "Service Maintenance Mode",
            "status": "critical",
            "notes": reason or "Maintenance mode is enabled for this "
                               "service, but no reason was provided.",
            "service_id": service_id,
            "service_name": entry.service.get("service", ""),
        })
        return True

    def disable_service_maintenance(self, service_id: str) -> bool:
        if self.local.services.get(service_id) is None:
            return False
        self.local.remove_check(f"_service_maintenance:{service_id}")
        return True

    def enable_node_maintenance(self, reason: str = "") -> None:
        self.local.add_check({
            "check_id": "_node_maintenance",
            "name": "Node Maintenance Mode",
            "status": "critical",
            "notes": reason or "Maintenance mode is enabled for this "
                               "node, but no reason was provided.",
        })

    def disable_node_maintenance(self) -> None:
        self.local.remove_check("_node_maintenance")

    def in_node_maintenance(self) -> bool:
        entry = self.local.checks.get("_node_maintenance")
        return entry is not None and not entry.deleted

    def add_check(self, defn: dict) -> None:
        cid = defn.get("check_id") or defn.get("name")
        runner = build_check_runner(
            defn, self._notify_check, alias_lookup=self._alias_lookup
        )
        record = {
            k: v
            for k, v in defn.items()
            if k in ("check_id", "name", "notes", "status", "service_id",
                     "service_name")
        }
        record.setdefault("name", cid)
        self.local.add_check(record)
        # Always retire any previous executor for this id — even when
        # the new definition is a bare catalog check with no runner —
        # so a replaced check can't keep pushing stale statuses.
        old = self.checks.pop(cid, None)
        if old:
            old.stop()
        if runner is not None:
            self.checks[cid] = runner
            runner.start()

    def _alias_lookup(self, service_ref: str):
        """Statuses of the checks attached to a local service (matched
        by id OR name), or None when no such service is registered
        (alias.go local path)."""
        ids = {
            ls.service.get("id") or ls.service.get("service")
            for ls in self.local.services.values()
            if not ls.deleted and (
                ls.service.get("id") == service_ref
                or ls.service.get("service") == service_ref
            )
        }
        if not ids:
            return None
        return [
            lc.check.get("status", "")
            for lc in self.local.checks.values()
            if not lc.deleted and lc.check.get("service_id") in ids
        ]

    def remove_check(self, check_id: str) -> bool:
        runner = self.checks.pop(check_id, None)
        if runner:
            runner.stop()
        return self.local.remove_check(check_id)

    def update_ttl_check(self, check_id: str, status: str, output: str = "") -> bool:
        """Agent TTL endpoints (pass/warn/fail)."""
        runner = self.checks.get(check_id)
        if not isinstance(runner, CheckTTL):
            return False
        runner.set_status(status, output)
        return True

    def _notify_check(self, check_id: str, status: str, output: str) -> None:
        self.local.update_check(check_id, status, output)

    # ------------------------------------------------------------------
    # user events (agent/user_event.go)
    # ------------------------------------------------------------------

    async def fire_event(self, name: str, payload: bytes = b"") -> str:
        """Fire a user event into the gossip plane
        (user_event.go:78 UserEvent → serf.UserEvent)."""
        await self.serf.user_event(name, payload)
        return str(uuid.uuid4())

    def _on_serf_event(self, event: Event) -> None:
        if event.type != EventType.USER:
            return
        key = (event.ltime, event.name)
        if key in self._event_seen:
            return  # agent-side dedup ring (user_event.go:118-130)
        self._event_seen.add(key)
        ue = UserEvent(
            id=str(uuid.uuid4()),
            name=event.name,
            payload=event.payload,
            ltime=event.ltime,
        )
        self.events.append(ue)
        self.event_index += 1
        if len(self.events) > USER_EVENT_BUFFER:
            dropped = self.events.pop(0)
            self._event_seen.discard((dropped.ltime, dropped.name))
        self._event_wake.set()
        self._event_wake = asyncio.Event()
        for handler in self.event_handlers:
            try:
                handler(ue)
            except Exception:  # noqa: BLE001
                log.exception("user event handler failed")

    def event_wake_handle(self) -> asyncio.Event:
        """Current wake event for long-polling /v1/event/list."""
        return self._event_wake
