"""The Server: raft quorum member owning the replicated catalog.

Equivalent of ``agent/consul/server.go`` + ``leader.go`` (SURVEY.md
§2.2): owns the raft node, FSM, state store, LAN serf pool, the RPC
listener, and — when leader — the reconcile/GC/session loops.

Wiring mirrored from the reference:

  serf tags           server_serf.go:35-120 — servers advertise
                      role/dc/id/expect and their RPC address in serf
                      node meta; peers discover each other from tags
  bootstrap           serf_server.go maybeBootstrap — wait until
                      bootstrap_expect servers are visible, then all
                      bootstrap the same deterministic voter set
  raft-over-RPC       server.go raftLayer — raft traffic is stream
                      type byte 1 on the shared RPC listener
  leader loop         leader.go:52,153 monitorLeadership/leaderLoop —
                      reconcile serf membership into the catalog,
                      add/remove raft peers, tombstone GC, session TTLs
  reconcile           leader.go:1075-1280 reconcileMember/
                      handleAliveMember/handleFailedMember/
                      handleLeftMember
  coordinate batching coordinate_endpoint.go:48 — Coordinate.Update
                      RPCs buffered and flushed as one raft entry per
                      CoordinateUpdatePeriod
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import Optional

from consul_tpu.agent import endpoints as eps
from consul_tpu.agent.fsm import ConsulFSM, MessageType
from consul_tpu.agent.rpc import (
    ERR_NO_LEADER,
    ERR_PERMISSION_DENIED,
    RPC_RAFT,
    RPCClient,
    RPCError,
    RPCServer,
    RaftRPCAdapter,
    rpc_timeout_for,
)
from consul_tpu.consensus.raft import NotLeaderError, RaftConfig, RaftNode
from consul_tpu.eventing.cluster import (
    Cluster,
    ClusterConfig,
    Event,
    EventType,
    Member,
    MemberStatus,
)
from consul_tpu.agent.router import Router
from consul_tpu.net.transport import Transport
from consul_tpu.protocol import LAN, WAN, GossipProfile
from consul_tpu.store.state import (
    HEALTH_CRITICAL,
    HEALTH_PASSING,
    SERF_CHECK_ID,
)

log = logging.getLogger("consul_tpu.server")

SERF_CHECK_NAME = "Serf Health Status"


@dataclasses.dataclass
class ServerConfig:
    node_name: str
    datacenter: str = "dc1"
    bootstrap_expect: int = 1
    profile: GossipProfile = LAN
    gossip_interval_scale: float = 1.0
    # Leader cadences (leader.go / config.go defaults, scaled down for
    # in-proc tests the same way the reference's test configs do).
    reconcile_interval_s: float = 60.0
    tombstone_ttl_s: float = 15 * 60.0
    tombstone_granularity_s: float = 30.0
    coordinate_update_period_s: float = 5.0
    session_ttl_sweep_s: float = 1.0
    # Raft timings forwarded to RaftConfig.
    raft_heartbeat_s: float = 0.05
    raft_election_min_s: float = 0.15
    raft_election_max_s: float = 0.30
    # WAN pool timing profile (config.go:314-327 DefaultWANConfig) and
    # LAN->WAN flooder cadence (agent/consul/flood.go loop).
    wan_profile: GossipProfile = WAN
    flood_interval_s: float = 1.0
    # Serf gossip snapshot + auto-rejoin (serf/snapshot.go, RejoinAfterLeave).
    serf_snapshot_path: str = ""
    rejoin_after_leave: bool = False
    # Autopilot (consul/autopilot/autopilot.go): dead raft servers are
    # pruned once they have been failed for the grace window, never
    # removing more than (voters-1)//2 so quorum is preserved.
    autopilot_cleanup_dead_servers: bool = True
    autopilot_interval_s: float = 10.0
    autopilot_grace_s: float = 10.0
    # autopilot.go promoteStableServers: a staging (non-voter) server is
    # promoted once continuously healthy for this long.
    autopilot_server_stabilization_s: float = 10.0
    # structs.AutopilotConfig MaxTrailingLogs: a server whose log trails
    # the leader by more than this is unhealthy.
    autopilot_max_trailing_logs: int = 250
    # Gossip encryption keyring (shared LAN/WAN, security.go).
    keyring: object = None
    # WAN replication (leader.go:834-979 + {acl,config}_replication.go):
    # non-primary DCs pull config entries + ACL policies/tokens from the
    # primary and converge their local raft state.
    primary_datacenter: str = ""
    replication_interval_s: float = 30.0
    acl_replication_token: str = ""
    # ACL system (agent/config: acl.enabled / default_policy / tokens.master).
    acl_enabled: bool = False
    acl_default_policy: str = "allow"   # "allow" | "deny"
    acl_master_token: str = ""
    acl_token_ttl_s: float = 30.0
    # acl_token_exp.go: leader sweep cadence for expired-token GC.
    acl_token_reap_interval_s: float = 5.0
    # leader_federation_state_ae.go: cadence for publishing this DC's
    # mesh-gateway set to the primary.
    federation_state_ae_interval_s: float = 30.0
    # auto_config_endpoint.go authorizer: when set, clients may
    # bootstrap via AutoConfig.InitialConfiguration with a JWT matching
    # this spec ({jwt_secret | jwt_validation_pub_keys, bound_issuer,
    # bound_audiences, claim_mappings, claim_assertions}).
    auto_config_authorizer: Optional[dict] = None
    # Network segments (server_serf.go:50 segmentLAN + types/area.go):
    # names of the additional LAN gossip rings this server bridges.
    # Clients join exactly ONE ring; servers join them all, so segment
    # members stay isolated from each other's gossip but every segment
    # reaches the servers.
    segments: tuple = ()


class Server:
    """One Consul server (``consul.Server``)."""

    def __init__(
        self,
        config: ServerConfig,
        gossip_transport: Transport,
        rpc_transport: Transport,
        wan_transport: Optional[Transport] = None,
        segment_transports: Optional[dict[str, Transport]] = None,
    ):
        self.config = config
        # Change-stream pub/sub fed by the FSM (stream/event_publisher.go
        # wired at state/memdb.go:37-41), served by Subscribe RPCs.
        from consul_tpu.stream import EventPublisher

        self.publisher = EventPublisher()
        self.fsm = ConsulFSM(publisher=self.publisher)
        self.store = self.fsm.store

        # ACL resolution against the replicated token/policy tables
        # (agent/consul/acl.go ACLResolver; cache TTL = ACLTokenTTL).
        from consul_tpu.acl import ACLResolver

        self.acl = ACLResolver(
            token_lookup=self.store.acl_token_get,
            policy_lookup=self.store.acl_policy_get,
            role_lookup=self.store.acl_role_get,
            enabled=config.acl_enabled,
            default_policy=config.acl_default_policy,
            master_token=config.acl_master_token,
            ttl_s=config.acl_token_ttl_s,
        )

        # RPC plane (port 8300 analogue; serf rides gossip_transport).
        self.rpc_transport = rpc_transport
        self.rpc_server = RPCServer(rpc_transport)
        self.rpc_client = RPCClient(rpc_transport)
        self._raft_rpc_client = RPCClient(rpc_transport, rpc_type=RPC_RAFT)
        self.raft_adapter = RaftRPCAdapter(
            self._raft_rpc_client, self._raft_peer_addr
        )
        self.rpc_server.bind_raft(self.raft_adapter.handle)

        # Gossip plane: LAN serf pool with server tags.
        lan_tags = {
            "role": "consul",
            "dc": config.datacenter,
            "id": config.node_name,
            "rpc_addr": rpc_transport.local_addr(),
            "expect": str(config.bootstrap_expect),
        }
        if wan_transport is not None:
            # Advertised so peers' flooders can join us into the WAN
            # pool (serf_flooder.go reads the wan port from tags).
            lan_tags["wan_addr"] = wan_transport.local_addr()
        self.serf = Cluster(
            ClusterConfig(
                name=config.node_name,
                tags=lan_tags,
                profile=config.profile,
                interval_scale=config.gossip_interval_scale,
                on_event=self._on_serf_event,
                snapshot_path=config.serf_snapshot_path or None,
                rejoin_after_leave=config.rejoin_after_leave,
                keyring=config.keyring,
            ),
            gossip_transport,
        )

        # WAN pool (server.go:506 setupSerf(WAN)): servers of every DC,
        # named "<node>.<dc>" (server_serf.go), slower timing profile.
        self.serf_wan: Optional[Cluster] = None
        if wan_transport is not None:
            self.serf_wan = Cluster(
                ClusterConfig(
                    name=f"{config.node_name}.{config.datacenter}",
                    tags={
                        "role": "consul",
                        "dc": config.datacenter,
                        "id": config.node_name,
                        "rpc_addr": rpc_transport.local_addr(),
                    },
                    profile=config.wan_profile,
                    interval_scale=config.gossip_interval_scale,
                    queue_events=False,  # router reads members directly
                    keyring=config.keyring,
                ),
                wan_transport,
            )
        self.router = Router(config.datacenter, self.serf_wan)

        # Segment rings (server_serf.go segmentLAN map): one extra serf
        # pool per configured segment, same server tags + the segment
        # name so clients of that ring discover us.
        self.segment_serfs: dict[str, Cluster] = {}
        for seg_name in config.segments:
            transport = (segment_transports or {}).get(seg_name)
            if transport is None:
                raise ValueError(
                    f"segment {seg_name!r} has no gossip transport"
                )
            self.segment_serfs[seg_name] = Cluster(
                ClusterConfig(
                    name=config.node_name,
                    tags={**lan_tags, "segment": seg_name},
                    profile=config.profile,
                    interval_scale=config.gossip_interval_scale,
                    keyring=config.keyring,
                ),
                transport,
            )

        # Mesh-gateway locator for wan federation (gateway_locator.go).
        from consul_tpu.connect.gateways import GatewayLocator

        self.gateway_locator = GatewayLocator(
            self.store, config.datacenter,
            config.primary_datacenter or config.datacenter,
        )

        self.raft: Optional[RaftNode] = None
        # Built-in Connect CA, created lazily on the leader (the private
        # key never leaves it; the root record replicates via raft).
        self._connect_ca = None
        self._connect_ca_lock = asyncio.Lock()
        self._bootstrap_disabled = False
        self._bootstrapping = False
        self._leader_tasks: list[asyncio.Task] = []
        self._tasks: list[asyncio.Task] = []
        self._reconcile_wake = asyncio.Event()
        self._coord_updates: dict[str, dict] = {}
        self._session_deadlines: dict[str, float] = {}
        self._tombstone_marks: list[tuple[float, int]] = []
        # Autopilot server-health records (autopilot.go clusterHealth)
        # + the static defaults replicated overrides layer over.
        self._server_health: dict[str, dict] = {}
        self._autopilot_defaults = {
            "autopilot_cleanup_dead_servers":
                config.autopilot_cleanup_dead_servers,
            "autopilot_grace_s": config.autopilot_grace_s,
            "autopilot_server_stabilization_s":
                config.autopilot_server_stabilization_s,
            "autopilot_max_trailing_logs":
                config.autopilot_max_trailing_logs,
        }
        self._shutdown = False

        # RPC endpoint services (server_oss.go:8-23).
        for name, ep in eps.build_endpoints(self).items():
            self.rpc_server.register(name, ep)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        await self.rpc_server.start()
        await self.serf.start()
        if self.serf_wan is not None:
            await self.serf_wan.start()
            self._tasks.append(asyncio.create_task(self._flood_loop()))
        for seg in self.segment_serfs.values():
            await seg.start()
            self._tasks.append(
                asyncio.create_task(self._segment_event_pump(seg))
            )
        self._tasks.append(asyncio.create_task(self._serf_event_pump()))
        # Snapshot auto-rejoin BEFORE bootstrap so a restarted server
        # re-discovers the established cluster instead of re-expecting
        # (serf/snapshot.go AliveNodes + server_serf.go RejoinAfterLeave).
        rejoined = await self.serf.auto_rejoin()
        if rejoined:
            log.info("auto-rejoined %d node(s) from gossip snapshot", rejoined)
        await self._maybe_bootstrap()

    async def join(self, addrs: list[str]) -> int:
        return await self.serf.join(addrs)

    async def join_segment(self, segment: str, addrs: list[str]) -> int:
        """Join peers of one segment ring (agent.go JoinLAN w/ segment
        port selection)."""
        seg = self.segment_serfs.get(segment)
        if seg is None:
            raise RPCError(f"unknown network segment {segment!r}")
        return await seg.join(addrs)

    async def _segment_event_pump(self, seg: Cluster) -> None:
        """Membership changes on a segment ring feed the same reconcile
        path as the main ring (server_serf.go lanEventHandler runs per
        segment)."""
        while not self._shutdown:
            await seg.events.get()
            self._reconcile_wake.set()

    async def join_wan(self, addrs: list[str]) -> int:
        """Join the WAN pool (server.go JoinWAN / `consul join -wan`)."""
        if self.serf_wan is None:
            raise RPCError("WAN gossip not configured")
        return await self.serf_wan.join(addrs)

    async def _flood_loop(self) -> None:
        """LAN→WAN flooder (agent/consul/flood.go:27-60 + router/
        serf_flooder.go): any server seen on the LAN but missing from
        the WAN pool gets joined in via its advertised wan_addr, so one
        explicit WAN join per DC suffices to federate every server."""
        while not self._shutdown:
            await asyncio.sleep(self.config.flood_interval_s)
            try:
                wan_names = {
                    m.tags.get("id")
                    for m in self.serf_wan.members.values()
                    if m.status == MemberStatus.ALIVE
                    and m.tags.get("dc") == self.config.datacenter
                }
                for m in list(self.serf.members.values()):
                    if (
                        m.status == MemberStatus.ALIVE
                        and m.tags.get("role") == "consul"
                        and m.tags.get("wan_addr")
                        and m.tags.get("id") not in wan_names
                    ):
                        await self.serf_wan.join([m.tags["wan_addr"]])
            except Exception:
                log.exception("flood loop failed")

    async def leave(self) -> None:
        # Graceful departure (server.go Leave): demote ourselves from
        # raft if possible, then leave serf.
        if self.raft and self.raft.is_leader() and len(self.raft.voters) > 1:
            try:
                await self.raft.remove_server(self.node_id)
            except Exception:  # noqa: BLE001 - best effort on the way out
                pass
        if self.serf_wan is not None:
            await self.serf_wan.leave()
        await self.serf.leave()

    async def shutdown(self) -> None:
        self._shutdown = True
        for t in self._tasks + self._leader_tasks:
            t.cancel()
        if self.raft:
            await self.raft.shutdown()
        if self.serf_wan is not None:
            await self.serf_wan.shutdown()
        for seg in self.segment_serfs.values():
            await seg.shutdown()
        await self.serf.shutdown()
        await self.rpc_client.shutdown()
        await self._raft_rpc_client.shutdown()
        await self.rpc_server.shutdown()

    @property
    def node_id(self) -> str:
        return self.config.node_name

    def is_leader(self) -> bool:
        return self.raft is not None and self.raft.is_leader()

    # ------------------------------------------------------------------
    # bootstrap & raft peer discovery (server_serf.go maybeBootstrap)
    # ------------------------------------------------------------------

    def _all_lan_members(self) -> list[Member]:
        """Union of the main ring and every segment ring, deduped by
        node name (a server appears in all rings — its main-ring record
        wins; a client lives in exactly one)."""
        merged: dict[str, Member] = {}
        for seg in self.segment_serfs.values():
            for m in seg.members.values():
                merged[m.name] = m
        for m in self.serf.members.values():
            merged[m.name] = m
        return list(merged.values())

    def _server_members(self) -> list[Member]:
        return [
            m
            for m in self.serf.members.values()
            if m.tags.get("role") == "consul"
            and m.tags.get("dc") == self.config.datacenter
        ]

    def _raft_peer_addr(self, node_id: str) -> Optional[str]:
        for m in self._server_members():
            if m.tags.get("id") == node_id:
                return m.tags.get("rpc_addr")
        return None

    async def _maybe_bootstrap(self) -> None:
        """Live-bootstrap guard dance (server_serf.go:318-401).

        Bootstrap only when (a) we have no raft state yet, (b) every
        visible server agrees on bootstrap_expect, and (c) NO visible
        server reports existing raft peers via Status.Peers.  A server
        that finds evidence of an established cluster disables its
        expect mode and starts raft as a non-voter follower instead —
        the leader's reconcile folds it in (handleAliveMember →
        add_voter), so a late joiner can never depose a live leader
        with a self-computed voter set.
        """
        if self.raft is not None or self._bootstrap_disabled or self._bootstrapping:
            return
        expect = self.config.bootstrap_expect
        servers = [
            m for m in self._server_members() if m.status == MemberStatus.ALIVE
        ]
        for m in servers:
            peer_expect = m.tags.get("expect")
            if peer_expect and int(peer_expect) != expect:
                log.error(
                    "%s: member %s has conflicting expect %s != %d; refusing bootstrap",
                    self.node_id, m.name, peer_expect, expect,
                )
                return
        if len(servers) < expect:
            return

        self._bootstrapping = True
        try:
            # Query each peer server; any reported raft peers is
            # evidence of an existing cluster (server_serf.go:365-401).
            for m in servers:
                if m.tags.get("id") == self.node_id:
                    continue
                addr = m.tags.get("rpc_addr")
                if not addr:
                    continue
                resp = None
                for attempt in range(3):
                    try:
                        resp = await self.rpc_client.call(
                            addr, "Status.Peers", {}, timeout=2.0
                        )
                        break
                    except Exception:  # noqa: BLE001 — unreachable peer
                        await asyncio.sleep(0.1 * (1 << attempt))
                if resp is None:
                    return  # retried on the next serf event
                if resp.get("peers"):
                    log.info(
                        "%s: existing raft peers reported by %s; disabling bootstrap",
                        self.node_id, m.name,
                    )
                    self._bootstrap_disabled = True
                    self._start_raft([])  # non-voter follower; leader adds us
                    return
            if self.raft is not None:
                return  # a concurrent path started raft while we probed
            # Initial voter set = every server visible when the expect
            # threshold is crossed; sorted so simultaneous bootstrappers
            # compute identical configs.
            voters = sorted(m.tags["id"] for m in servers)
            if self.node_id not in voters:
                voters.append(self.node_id)
            self._start_raft(sorted(voters))
            log.info("%s: raft bootstrapped with voters %s", self.node_id, voters)
        finally:
            self._bootstrapping = False

    def _start_raft(self, voters: list[str]) -> None:
        self.raft = RaftNode(
            RaftConfig(
                node_id=self.node_id,
                heartbeat_interval=self.config.raft_heartbeat_s,
                election_timeout_min=self.config.raft_election_min_s,
                election_timeout_max=self.config.raft_election_max_s,
            ),
            self.fsm,
            self.raft_adapter,
            voters,
        )
        self.raft.leadership_listeners.append(self._on_leadership)
        self._tasks.append(asyncio.create_task(self.raft.start()))

    # ------------------------------------------------------------------
    # RPC helpers used by endpoints
    # ------------------------------------------------------------------

    def acl_resolve(self, body: dict):
        """Token from QueryOptions → Authorizer; unknown tokens surface
        as an RPC error (consul/acl.go ResolveToken)."""
        from consul_tpu.acl.engine import ACLError

        try:
            return self.acl.resolve(body.get("token", "") or "")
        except ACLError as e:
            raise RPCError(str(e)) from e

    def acl_check(self, body: dict, kind: str, name: str, want: str,
                  whole_subtree: bool = False) -> None:
        """Enforce one resource permission; raises the reference's
        'Permission denied' (acl.ErrPermissionDenied) on failure.
        Requests bound for another DC are enforced THERE — token tables
        are per-datacenter (the reference replicates them; we don't).
        ``whole_subtree`` (key resource only) requires write over every
        configured rule under the prefix (acl.go KeyWritePrefix) — the
        delete-tree guard."""
        if not self.acl.enabled:
            return
        dc = body.get("dc")
        if dc and dc != self.config.datacenter:
            return
        authz = self.acl_resolve(body)
        if whole_subtree:
            ok = authz.key_write_prefix(name)
        else:
            ok = authz.allowed(kind, name, want)
        if not ok:
            raise RPCError(ERR_PERMISSION_DENIED)

    def leader_rpc_addr(self) -> Optional[str]:
        if self.raft is None or self.raft.leader_id is None:
            return None
        return self._raft_peer_addr(self.raft.leader_id)

    async def forward(
        self, method: str, body: dict, *, read: bool = False
    ) -> Optional[dict]:
        """Forward to the right datacenter, then to the leader unless we
        are it (rpc.go:577-614 forward: the dc check comes FIRST —
        a request for another dc goes over the WAN regardless of our
        leadership or the read's staleness).

        Returns None when the caller should execute locally, else the
        remote response.  Only *reads* honor allow_stale — a write
        carrying a recycled query-options dict must still reach the
        leader (the reference's forward() checks info.IsRead()).
        """
        dc = body.get("dc")
        if dc and dc != self.config.datacenter:
            return await self._forward_dc(method, body, dc)
        if read and body.get("allow_stale"):
            return None
        if self.raft is not None and self.raft.is_leader():
            return None
        addr = self.leader_rpc_addr()
        if addr is None:
            raise RPCError(ERR_NO_LEADER)
        return await self.rpc_client.call(
            addr, method, body, timeout=rpc_timeout_for(body)
        )

    async def _forward_dc(self, method: str, body: dict, dc: str) -> dict:
        """rpc.go:617-655 forwardDC: pick a server of the target DC from
        the router (WAN-discovered) and relay the call; try a couple of
        candidates before giving up."""
        servers = self.router.servers_in_dc(dc)
        if not servers:
            raise RPCError(f"no path to datacenter {dc}")
        last: Optional[Exception] = None
        for meta in servers[:2]:
            try:
                return await self.rpc_client.call(
                    meta.rpc_addr, method, body, timeout=rpc_timeout_for(body)
                )
            except Exception as e:  # noqa: BLE001 - try the next server
                last = e
        raise RPCError(f"rpc to datacenter {dc} failed: {last}")

    async def raft_apply(self, msg_type: MessageType, body: dict):
        """Apply a command through raft (rpc.go:679 raftApply)."""
        if self.raft is None:
            raise RPCError(ERR_NO_LEADER)
        try:
            result = await self.raft.apply({"type": int(msg_type), "body": body})
        except NotLeaderError as e:
            raise RPCError(ERR_NO_LEADER) from e
        if isinstance(result, dict) and "error" in result and len(result) == 1:
            raise RPCError(result["error"])
        return result

    async def connect_ca(self):
        """The leader's signing authority (leader_connect.go
        initializeCA): first use generates a root and replicates its
        record.  A failover leader mints a fresh root (rotation without
        cross-signing); old roots stay stored so outstanding leaves
        verify until expiry."""
        async with self._connect_ca_lock:  # single-flight initialization
            if self._connect_ca is None:
                from consul_tpu.connect import BuiltinCA

                _, roots = self.store.ca_roots()
                trust = next(
                    (r.get("trust_domain") for r in roots
                     if r.get("trust_domain")),
                    None,
                )
                ca = BuiltinCA(self.config.datacenter, trust_domain=trust)
                root = ca.generate_root()
                await self.raft_apply(
                    MessageType.CONNECT_CA, {"op": "set-root", "root": root}
                )
                self._connect_ca = ca
            return self._connect_ca

    async def consistent_barrier(self) -> None:
        """Leader linearizability fence for require_consistent reads
        (the reference's VerifyLeader in blockingQuery preamble)."""
        if self.raft is None:
            raise RPCError(ERR_NO_LEADER)
        try:
            await self.raft.barrier()
        except NotLeaderError as e:
            raise RPCError(ERR_NO_LEADER) from e

    # ------------------------------------------------------------------
    # serf event plumbing
    # ------------------------------------------------------------------

    def _on_serf_event(self, event: Event) -> None:
        if event.type in (
            EventType.MEMBER_JOIN,
            EventType.MEMBER_FAILED,
            EventType.MEMBER_LEAVE,
            EventType.MEMBER_REAP,
            EventType.MEMBER_UPDATE,
        ):
            self._reconcile_wake.set()

    async def _serf_event_pump(self) -> None:
        """Server-side event loop (server_serf.go lanEventHandler):
        membership changes trigger bootstrap checks and reconcile."""
        while not self._shutdown:
            await self.serf.events.get()
            self._reconcile_wake.set()
            await self._maybe_bootstrap()

    # ------------------------------------------------------------------
    # leader loops (leader.go)
    # ------------------------------------------------------------------

    def _on_leadership(self, leader: bool) -> None:
        if leader:
            self._leader_tasks = [
                asyncio.create_task(self._reconcile_loop()),
                asyncio.create_task(self._tombstone_gc_loop()),
                asyncio.create_task(self._session_ttl_loop()),
                asyncio.create_task(self._coordinate_flush_loop()),
                asyncio.create_task(self._autopilot_loop()),
                asyncio.create_task(self._replication_loop()),
                asyncio.create_task(self._acl_token_reap_loop()),
                asyncio.create_task(self._federation_state_ae_loop()),
            ]
            self._reconcile_wake.set()
        else:
            for t in self._leader_tasks:
                t.cancel()
            self._leader_tasks = []
            self._session_deadlines.clear()

    async def _reconcile_loop(self) -> None:
        while True:
            try:
                await asyncio.wait_for(
                    self._reconcile_wake.wait(),
                    timeout=self.config.reconcile_interval_s,
                )
            except asyncio.TimeoutError:
                pass
            self._reconcile_wake.clear()
            try:
                await self._reconcile()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — a leader loop must
                # survive transient apply timeouts / malformed tags; it
                # retries on the next tick (leader.go leaderLoop).
                log.warning("%s: reconcile failed: %s", self.node_id, e)

    async def _reconcile(self) -> None:
        """Fold serf membership into the catalog and the raft config
        (leader.go:1075-1280).  The gossip plane is the source of truth
        for node liveness; the catalog follows it."""
        _, catalog_nodes = self.store.nodes()
        known = {n["node"] for n in catalog_nodes}

        for m in self._all_lan_members():
            if m.status == MemberStatus.ALIVE:
                await self._handle_alive_member(m)
            elif m.status == MemberStatus.FAILED:
                await self._handle_failed_member(m)
            elif m.status == MemberStatus.LEFT:
                await self._handle_left_member(m)
            known.discard(m.name)

        # reconcileReaped: catalog nodes with a serfHealth check that
        # serf no longer knows at all are deregistered.
        for name in known:
            _, checks = self.store.node_checks(name)
            if any(c["check_id"] == SERF_CHECK_ID for c in checks):
                await self.raft_apply(MessageType.DEREGISTER, {"node": name})

    def _member_needs_update(self, m: Member, status: str) -> bool:
        _, node = self.store.node(m.name)
        if node is None or node.get("address") != m.addr:
            return True
        _, checks = self.store.node_checks(m.name)
        serf_check = next(
            (c for c in checks if c["check_id"] == SERF_CHECK_ID), None
        )
        return serf_check is None or serf_check["status"] != status

    def _is_peer_server(self, m: Member) -> bool:
        """Server of OUR datacenter (voter changes must never cross
        DCs — _server_members applies the same filter)."""
        return (
            m.tags.get("role") == "consul"
            and m.tags.get("dc") == self.config.datacenter
            and bool(m.tags.get("id"))
        )

    async def _handle_alive_member(self, m: Member) -> None:
        if self._is_peer_server(m) and self.raft is not None:
            sid = m.tags["id"]
            if sid not in self.raft.voters and \
                    sid not in self.raft.non_voters:
                # New servers join as STAGING non-voters; autopilot
                # promotes them once stable (leader.go joinConsulServer
                # → AddNonvoter under raft protocol 3, then
                # autopilot.promoteStableServers).
                await self.raft.add_nonvoter(sid)
        if not self._member_needs_update(m, HEALTH_PASSING):
            return
        await self.raft_apply(
            MessageType.REGISTER,
            {
                "node": m.name,
                "address": m.addr,
                "node_meta": {
                    "serf": "1",
                    **({"segment": m.tags["segment"]}
                       if m.tags.get("segment") else {}),
                },
                "check": {
                    "check_id": SERF_CHECK_ID,
                    "name": SERF_CHECK_NAME,
                    "status": HEALTH_PASSING,
                    "output": "Agent alive and reachable",
                },
            },
        )

    async def _handle_failed_member(self, m: Member) -> None:
        if not self._member_needs_update(m, HEALTH_CRITICAL):
            return
        await self.raft_apply(
            MessageType.REGISTER,
            {
                "node": m.name,
                "address": m.addr,
                "check": {
                    "check_id": SERF_CHECK_ID,
                    "name": SERF_CHECK_NAME,
                    "status": HEALTH_CRITICAL,
                    "output": "Agent not live or unreachable",
                },
            },
        )

    async def _handle_left_member(self, m: Member) -> None:
        if m.name == self.node_id:
            return  # never deregister ourselves (leader.go:1217)
        if self._is_peer_server(m) and self.raft is not None:
            if m.tags["id"] in self.raft.voters:
                await self.raft.remove_server(m.tags["id"])
        _, node = self.store.node(m.name)
        if node is not None:
            await self.raft_apply(MessageType.DEREGISTER, {"node": m.name})

    def apply_autopilot_overrides(self) -> None:
        """Fold the replicated autopilot-config entry (Operator.
        AutopilotSetConfiguration) over the STATIC defaults captured at
        construction — never over previously-mutated values, so the
        effective settings are a pure function of replicated state and
        identical on every (re)elected leader."""
        mapping = {
            "cleanup_dead_servers": "autopilot_cleanup_dead_servers",
            "last_contact_threshold_s": "autopilot_grace_s",
            "server_stabilization_time_s":
                "autopilot_server_stabilization_s",
            "max_trailing_logs": "autopilot_max_trailing_logs",
        }
        _, entry = self.store.config_entry_get("autopilot-config", "global")
        entry = entry or {}
        for key, field in mapping.items():
            setattr(
                self.config, field,
                entry.get(key, self._autopilot_defaults[field]),
            )

    def _autopilot_update_health(self) -> None:
        """autopilot.go serverHealthLoop/updateClusterHealth: score each
        peer server — serf-alive AND raft log within MaxTrailingLogs of
        the leader — and track how long it has been CONTINUOUSLY
        healthy (StableSince resets on any unhealthy observation)."""
        raft = self.raft
        is_leader = raft is not None and raft.is_leader()
        now = time.monotonic()
        seen = set()
        for m in list(self.serf.members.values()):
            if not self._is_peer_server(m):
                continue
            sid = m.tags["id"]
            seen.add(sid)
            alive = m.status == MemberStatus.ALIVE
            rec = self._server_health.get(sid)
            # Log lag is LEADER knowledge (match_index lives on the
            # leader's replicators) — followers score serf health only,
            # and a fresh leader whose match_index hasn't converged yet
            # (0 right after election) keeps the PREVIOUS verdict
            # instead of resetting every stabilization clock on each
            # failover.
            healthy = alive
            if is_leader and sid != self.node_id:
                m_idx = raft._match_index.get(sid, 0)
                if m_idx > 0 or raft.last_index() == 0:
                    lag = raft.last_index() - m_idx
                    healthy = alive and \
                        lag <= self.config.autopilot_max_trailing_logs
                elif rec is not None:
                    healthy = alive and rec["healthy"]
            if rec is None or rec["healthy"] != healthy:
                rec = {"healthy": healthy, "stable_since": now}
            if is_leader and sid != self.node_id:
                rec["last_index"] = raft._match_index.get(sid, 0)
            elif sid == self.node_id and raft is not None:
                rec["last_index"] = raft.last_index()
            else:
                # A follower has no view of other servers' match index —
                # report 0 rather than fabricating one.
                rec["last_index"] = 0
            self._server_health[sid] = rec
        for sid in list(self._server_health):
            if sid not in seen:
                del self._server_health[sid]

    async def _autopilot_loop(self) -> None:
        """autopilot.go run(): each pass promotes stable staging servers
        and prunes dead ones.

        promotion   a non-voter continuously healthy for
                    ServerStabilizationTime becomes a voter
                    (promoteStableServers)
        pruning     voters/non-voters whose serf member has been FAILED
                    past the grace window are removed — never more than
                    (voters-1)//2 voters in one pass, so a partition
                    can't talk the leader into destroying its own
                    quorum (autopilot.go removalLimit)
        """
        while not self._shutdown:
            await asyncio.sleep(self.config.autopilot_interval_s)
            try:
                if self.raft is None or not self.raft.is_leader():
                    continue
                self.apply_autopilot_overrides()
                self._autopilot_update_health()
                now = time.monotonic()

                # -- promote stable non-voters -------------------------
                for sid in list(self.raft.non_voters):
                    rec = self._server_health.get(sid)
                    if (
                        rec is not None
                        and rec["healthy"]
                        and now - rec["stable_since"]
                        >= self.config.autopilot_server_stabilization_s
                    ):
                        log.info("autopilot: promoting server %s", sid)
                        await self.raft.promote_server(sid)

                if not self.config.autopilot_cleanup_dead_servers:
                    continue
                # -- prune dead servers --------------------------------
                dead_voters, dead_staging = [], []
                for m in list(self.serf.members.values()):
                    sid = m.tags.get("id")
                    if (
                        self._is_peer_server(m)
                        and m.status == MemberStatus.FAILED
                        and sid != self.node_id
                        and (m.leave_time or now) + self.config.autopilot_grace_s
                        <= now
                    ):
                        if sid in self.raft.voters:
                            dead_voters.append(sid)
                        elif sid in self.raft.non_voters:
                            dead_staging.append(sid)
                # Dead staging servers cost no quorum — drop them all.
                for node_id in dead_staging:
                    log.info("autopilot: removing dead staging server %s",
                             node_id)
                    await self.raft.remove_server(node_id)
                limit = max((len(self.raft.voters) - 1) // 2, 0)
                for node_id in dead_voters[:limit]:
                    log.info("autopilot: removing dead server %s", node_id)
                    await self.raft.remove_server(node_id)
            except Exception:
                log.exception("autopilot loop failed")

    def _is_secondary(self) -> bool:
        return bool(
            self.config.primary_datacenter
            and self.config.primary_datacenter != self.config.datacenter
        )

    async def _replication_loop(self) -> None:
        """Primary→secondary replication (config_replication.go +
        acl_replication.go + federation_state_replication.go):
        rate-limited pull loops on the secondary's leader; remote state
        is diffed against local and converged through the local raft."""
        if not self._is_secondary():
            return
        while not self._shutdown:
            await asyncio.sleep(self.config.replication_interval_s)
            try:
                if self.raft is None or not self.raft.is_leader():
                    continue
                await self._replicate_config_entries()
                await self._replicate_acl()
                await self._replicate_federation_states()
            except Exception:
                log.exception("replication round failed")

    async def _federation_state_ae_loop(self) -> None:
        """Every DC's leader publishes its own mesh-gateway set to the
        PRIMARY's raft (leader_federation_state_ae.go
        FederationStateAntiEntropy); secondaries then pull the full map
        back via _replicate_federation_states, so each DC learns every
        other DC's gateways."""
        while True:
            await asyncio.sleep(self.config.federation_state_ae_interval_s)
            try:
                own = self.gateway_locator.build_own_state()
                # Skip the write when the published state already
                # matches (the reference diffs content before writing
                # for the same reason: no raft churn).  An EMPTY set
                # still publishes over a non-empty record — losing the
                # last gateway must prune the stale addresses everywhere
                # (leader_federation_state_ae.go replicates deletions
                # the same way).
                _, current = self.store.federation_state_get(
                    self.config.datacenter
                )
                if current is None:
                    if not own["mesh_gateways"]:
                        continue  # nothing to advertise yet
                elif self._strip_indexes(current) == own:
                    continue
                await self.rpc_server.dispatch_local(
                    "FederationState.Apply",
                    {"op": "upsert", "state": own,
                     "token": self.config.acl_replication_token
                     or self.config.acl_master_token},
                )
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — retry next tick
                log.warning(
                    "%s: federation state anti-entropy failed: %s",
                    self.node_id, e,
                )

    async def _replicate_federation_states(self) -> None:
        """Pull every DC's federation state from the primary
        (federation_state_replication.go).  Own-DC state is replicated
        too — the AE loop is the writer of record and re-pushes if the
        catalog moved on."""
        primary = self.config.primary_datacenter
        out = await self._forward_dc(
            "FederationState.List",
            {"dc": primary, "token": self.config.acl_replication_token},
            primary,
        )
        remote = {s["datacenter"]: self._strip_indexes(s)
                  for s in out.get("states", [])}
        _, local_list = self.store.federation_state_list()
        local = {s["datacenter"]: self._strip_indexes(s)
                 for s in local_list}
        for dc, state in remote.items():
            if local.get(dc) != state:
                await self.raft_apply(
                    MessageType.FEDERATION_STATE,
                    {"op": "upsert", "state": state},
                )
        for dc in set(local) - set(remote):
            await self.raft_apply(
                MessageType.FEDERATION_STATE,
                {"op": "delete", "state": {"datacenter": dc}},
            )

    @staticmethod
    def _strip_indexes(rec: dict) -> dict:
        return {k: v for k, v in rec.items()
                if k not in ("create_index", "modify_index")}

    async def _replicate_config_entries(self) -> None:
        primary = self.config.primary_datacenter
        out = await self._forward_dc(
            "ConfigEntry.List",
            {"dc": primary, "token": self.config.acl_replication_token},
            primary,
        )
        # Autopilot settings are per-DC (the reference keeps them in a
        # separate table); never replicate or delete them.
        remote = {(e["kind"], e["name"]): self._strip_indexes(e)
                  for e in out.get("entries", [])
                  if e.get("kind") != "autopilot-config"}
        _, local_list = self.store.config_entries_by_kind(None)
        local = {(e["kind"], e["name"]): self._strip_indexes(e)
                 for e in local_list
                 if e.get("kind") != "autopilot-config"}
        for key, entry in remote.items():
            if local.get(key) != entry:
                await self.raft_apply(
                    MessageType.CONFIG_ENTRY, {"op": "set", "entry": entry}
                )
        for kind, name in set(local) - set(remote):
            await self.raft_apply(
                MessageType.CONFIG_ENTRY,
                {"op": "delete", "entry": {"kind": kind, "name": name}},
            )

    async def _replicate_acl(self) -> None:
        """ACL policies + tokens from the primary (acl_replication.go;
        needs an acl:write replication token or the primary redacts
        secrets, which we refuse to store)."""
        primary = self.config.primary_datacenter
        token = self.config.acl_replication_token
        pol_out = await self._forward_dc(
            "ACL.PolicyList", {"dc": primary, "token": token}, primary
        )
        remote_pols = {p["id"]: self._strip_indexes(p)
                       for p in pol_out.get("policies", [])}
        _, local_list = self.store.acl_policy_list()
        local_pols = {p["id"]: self._strip_indexes(p) for p in local_list}
        for pid, pol in remote_pols.items():
            if local_pols.get(pid) != pol:
                await self.raft_apply(
                    MessageType.ACL_POLICY_SET, {"policy": pol}
                )
        for pid in set(local_pols) - set(remote_pols):
            await self.raft_apply(
                MessageType.ACL_POLICY_DELETE, {"id": pid}
            )
        # Replicated policy changes must flush cached authorizers even
        # when token replication below is skipped.
        self.acl.invalidate()

        tok_out = await self._forward_dc(
            "ACL.TokenList", {"dc": primary, "token": token}, primary
        )
        remote_toks = {}
        for t in tok_out.get("tokens", []):
            if t.get("secret_id") == "<hidden>":
                log.warning(
                    "ACL replication token lacks acl:write on the "
                    "primary; skipping token replication"
                )
                break
            remote_toks[t["secret_id"]] = self._strip_indexes(t)
        else:
            _, local_tok_list = self.store.acl_token_list()
            local_toks = {t["secret_id"]: self._strip_indexes(t)
                          for t in local_tok_list}
            for sid, tok in remote_toks.items():
                if local_toks.get(sid) != tok:
                    await self.raft_apply(
                        MessageType.ACL_TOKEN_SET, {"token": tok}
                    )
            for sid in set(local_toks) - set(remote_toks):
                # DC-local tokens survive replication: management tokens
                # (a secondary's own bootstrap) and tokens marked local
                # (the reference's token.Local flag, acl_replication.go).
                t = local_toks[sid]
                if t.get("type") == "management" or t.get("local"):
                    continue
                await self.raft_apply(
                    MessageType.ACL_TOKEN_DELETE, {"secret_id": sid}
                )
            self.acl.invalidate()  # token set changed too

    async def _tombstone_gc_loop(self) -> None:
        """Time-based tombstone reaping (leader.go:292 + tombstone GC):
        the leader snapshots (now, kv index) marks and raft-applies a
        reap for the index recorded tombstone_ttl ago."""
        while True:
            await asyncio.sleep(self.config.tombstone_granularity_s)
            now = time.monotonic()
            self._tombstone_marks.append((now, self.store.max_index("kvs", "tombstones")))
            cutoff_idx = 0
            keep: list[tuple[float, int]] = []
            for ts, idx in self._tombstone_marks:
                if now - ts >= self.config.tombstone_ttl_s:
                    cutoff_idx = max(cutoff_idx, idx)
                else:
                    keep.append((ts, idx))
            self._tombstone_marks = keep
            if cutoff_idx:
                try:
                    await self.raft_apply(
                        MessageType.TOMBSTONE, {"op": "reap", "index": cutoff_idx}
                    )
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — retry next tick
                    log.warning("%s: tombstone reap failed: %s", self.node_id, e)
                    self._tombstone_marks.append((0.0, cutoff_idx))

    async def _acl_token_reap_loop(self) -> None:
        """Delete expired ACL tokens through raft (acl_token_exp.go
        startACLTokenReaping: periodic sweep on the leader; expired
        tokens already fail resolution, this is garbage collection)."""
        while True:
            await asyncio.sleep(self.config.acl_token_reap_interval_s)
            for rec in self.store.acl_tokens_expired(time.time()):
                try:
                    await self.raft_apply(
                        MessageType.ACL_TOKEN_DELETE,
                        {"secret_id": rec["secret_id"]},
                    )
                    self.acl.invalidate(rec["secret_id"])
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — retry next sweep
                    log.warning(
                        "%s: expired token reap failed: %s", self.node_id, e
                    )

    async def _session_ttl_loop(self) -> None:
        """Invalidate sessions whose TTL lapsed without renewal
        (session_ttl.go: timers at 2x TTL on the leader)."""
        while True:
            await asyncio.sleep(self.config.session_ttl_sweep_s)
            now = time.monotonic()
            _, sessions = self.store.session_list()
            live = set()
            for sess in sessions:
                ttl = _parse_ttl(sess.get("ttl"))
                if ttl <= 0:
                    continue
                sid = sess["id"]
                live.add(sid)
                deadline = self._session_deadlines.setdefault(sid, now + 2 * ttl)
                if now >= deadline:
                    try:
                        await self.raft_apply(
                            MessageType.SESSION,
                            {"op": "destroy", "session": {"id": sid}},
                        )
                        self._session_deadlines.pop(sid, None)
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:  # noqa: BLE001 — retry next sweep
                        log.warning(
                            "%s: session %s invalidation failed: %s",
                            self.node_id, sid, e,
                        )
            for sid in list(self._session_deadlines):
                if sid not in live:
                    del self._session_deadlines[sid]

    def renew_session(self, sid: str, ttl: float) -> None:
        self._session_deadlines[sid] = time.monotonic() + 2 * ttl

    # -- coordinates ---------------------------------------------------

    def stage_coordinate_update(self, node: str, segment: str, coord: dict) -> None:
        self._coord_updates[f"{node}\x00{segment}"] = {
            "node": node,
            "segment": segment,
            "coord": coord,
        }

    async def _coordinate_flush_loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.coordinate_update_period_s)
            if not self._coord_updates:
                continue
            updates = list(self._coord_updates.values())
            self._coord_updates.clear()
            try:
                await self.raft_apply(
                    MessageType.COORDINATE_BATCH_UPDATE, {"updates": updates}
                )
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — restage for next flush
                log.warning("%s: coordinate flush failed: %s", self.node_id, e)
                for u in updates:
                    self._coord_updates.setdefault(
                        f"{u['node']}\x00{u['segment']}", u
                    )


def _parse_ttl(ttl) -> float:
    """'10s' / '1m' / numeric seconds → seconds (api session TTL)."""
    if ttl in (None, ""):
        return 0.0
    if isinstance(ttl, (int, float)):
        return float(ttl)
    s = str(ttl)
    try:
        if s.endswith("ms"):
            return float(s[:-2]) / 1000.0
        if s.endswith("s"):
            return float(s[:-1])
        if s.endswith("m"):
            return float(s[:-1]) * 60.0
        if s.endswith("h"):
            return float(s[:-1]) * 3600.0
        return float(s)
    except ValueError:
        return 0.0
