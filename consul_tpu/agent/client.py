"""The Client: a thin agent that forwards all RPCs to servers.

Equivalent of ``agent/consul/client.go`` + ``agent/router/manager.go``:
LAN serf membership only (no raft), a server list maintained from serf
member tags, and RPC forwarding with rebalancing and
retry-on-failure/no-leader (client.go:237-280).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import random
import time
from typing import Optional

from consul_tpu.agent.rpc import (
    ERR_NO_LEADER,
    RPCClient,
    RPCError,
    rpc_timeout_for,
)
from consul_tpu.eventing.cluster import Cluster, ClusterConfig, MemberStatus
from consul_tpu.net.transport import Transport
from consul_tpu.protocol import LAN, GossipProfile

log = logging.getLogger("consul_tpu.client")

RPC_HOLD_TIMEOUT = 7.0  # config.go RPCHoldTimeout
RPC_RETRIES = 3


@dataclasses.dataclass
class ClientConfig:
    node_name: str
    datacenter: str = "dc1"
    profile: GossipProfile = LAN
    gossip_interval_scale: float = 1.0
    tags: dict = dataclasses.field(default_factory=dict)
    keyring: object = None  # gossip encryption (security.go)


REBALANCE_INTERVAL_S = 120.0  # router/manager.go clientRPCMinReuseDuration


class ServerManager:
    """Tracks known servers from serf tags, rotates through them
    (router/manager.go:44-190): sticky preferred server, cycled on
    failure and periodically re-shuffled so client load spreads over
    servers added later."""

    def __init__(
        self,
        serf: Cluster,
        datacenter: str,
        seed: int = 0,
        rebalance_interval_s: float = REBALANCE_INTERVAL_S,
    ):
        self.serf = serf
        self.datacenter = datacenter
        self._rng = random.Random(seed)
        self._preferred: Optional[str] = None  # rpc addr
        self.rebalance_interval_s = rebalance_interval_s
        self._next_rebalance = 0.0

    def servers(self) -> list[dict]:
        out = []
        for m in self.serf.members.values():
            if (
                m.status == MemberStatus.ALIVE
                and m.tags.get("role") == "consul"
                and m.tags.get("dc") == self.datacenter
                and m.tags.get("rpc_addr")
            ):
                out.append({
                    "name": m.name,
                    "id": m.tags.get("id", m.name),
                    "rpc_addr": m.tags["rpc_addr"],
                })
        return out

    def pick(self) -> Optional[str]:
        servers = self.servers()
        if not servers:
            return None
        addrs = [s["rpc_addr"] for s in servers]
        now = time.monotonic()
        if self._preferred in addrs and now < self._next_rebalance:
            return self._preferred
        self._preferred = self._rng.choice(addrs)
        self._next_rebalance = now + self.rebalance_interval_s
        return self._preferred

    def notify_failed(self, addr: str) -> None:
        if self._preferred == addr:
            self._preferred = None


class Client:
    """One Consul client agent (``consul.Client``)."""

    def __init__(
        self,
        config: ClientConfig,
        gossip_transport: Transport,
        rpc_transport: Transport,
    ):
        self.config = config
        tags = {"role": "node", "dc": config.datacenter, **config.tags}
        self.serf = Cluster(
            ClusterConfig(
                name=config.node_name,
                tags=tags,
                profile=config.profile,
                interval_scale=config.gossip_interval_scale,
                keyring=config.keyring,
            ),
            gossip_transport,
        )
        self.rpc_client = RPCClient(rpc_transport)
        self.routers = ServerManager(self.serf, config.datacenter)

    async def start(self) -> None:
        await self.serf.start()

    async def join(self, addrs: list[str]) -> int:
        return await self.serf.join(addrs)

    async def leave(self) -> None:
        await self.serf.leave()

    async def shutdown(self) -> None:
        await self.rpc_client.shutdown()
        await self.serf.shutdown()

    async def rpc(self, method: str, body: dict, timeout: float = 0.0):
        """Forward an RPC to a server, retrying with jitter across
        servers on connection failure or missing leader
        (client.go:237-280 RPC retry loop).  With no explicit timeout
        the budget follows the query's blocking wait."""
        timeout = timeout or rpc_timeout_for(body)
        last_error: Exception = RPCError("no known consul servers")
        for attempt in range(RPC_RETRIES):
            addr = self.routers.pick()
            if addr is None:
                await asyncio.sleep(0.05 * (attempt + 1))
                continue
            try:
                return await self.rpc_client.call(addr, method, body, timeout)
            except ConnectionError as e:
                self.routers.notify_failed(addr)
                last_error = e
            except RPCError as e:
                if ERR_NO_LEADER in str(e):
                    # Leader election in progress: back off and retry
                    # (rpc.go holds for RPCHoldTimeout under no-leader).
                    last_error = e
                    await asyncio.sleep(
                        min(RPC_HOLD_TIMEOUT / 8 * (attempt + 1), 1.0)
                    )
                else:
                    raise
        raise last_error
