"""The replicated state machine: raft log entries → StateStore writes.

Equivalent of the reference's ``agent/consul/fsm`` package: a dispatch
table from message type to a command handler built at init
(``fsm/fsm.go:19-120``), the command handlers themselves
(``fsm/commands_oss.go:13-40``), and whole-store snapshot/restore
(``fsm/snapshot_oss.go``).

Raft entry payloads are ``{"type": MessageType, "body": {...}}`` dicts
(the reference encodes the type as the first byte of the msgpack buffer,
``structs.Encode``); bodies are msgpack-friendly dicts throughout.

A message type OR'd with ``IGNORE_UNKNOWN_FLAG`` (bit 7) is skipped
without error when this node doesn't understand it — the reference's
forward-compatibility rule (``structs/structs.go`` IgnoreUnknownTypeFlag).
"""

from __future__ import annotations

import enum
import logging
from typing import Any, Callable, Optional

from consul_tpu.consensus.raft import FSM, Entry
from consul_tpu.store.state import StateStore

log = logging.getLogger("consul_tpu.fsm")

IGNORE_UNKNOWN_FLAG = 128  # structs/structs.go IgnoreUnknownTypeFlag


class MessageType(enum.IntEnum):
    """Raft command types (``agent/structs/structs.go`` MessageType
    consts; same numbering so snapshots stay comparable)."""

    REGISTER = 0
    DEREGISTER = 1
    KVS = 2
    SESSION = 3
    ACL = 4  # deprecated legacy ACL path (unused, reserved)
    TOMBSTONE = 5
    COORDINATE_BATCH_UPDATE = 6
    PREPARED_QUERY = 7
    TXN = 8
    AUTOPILOT = 9
    AREA = 10
    ACL_BOOTSTRAP = 11
    INTENTION = 12
    CONNECT_CA = 13
    ACL_TOKEN_SET = 17
    ACL_TOKEN_DELETE = 18
    ACL_POLICY_SET = 19
    ACL_POLICY_DELETE = 20
    CONFIG_ENTRY = 22
    FEDERATION_STATE = 27


class ConsulFSM(FSM):
    """Applies committed raft entries to a :class:`StateStore`.

    The FSM is the ONLY writer to the store on a server, so every read
    is a consistent snapshot at some raft index (``fsm/fsm.go:102``).
    """

    def __init__(self, store: Optional[StateStore] = None):
        self.store = store or StateStore()
        self._handlers: dict[int, Callable[[int, dict], Any]] = {
            MessageType.REGISTER: self._apply_register,
            MessageType.DEREGISTER: self._apply_deregister,
            MessageType.KVS: self._apply_kvs,
            MessageType.SESSION: self._apply_session,
            MessageType.TOMBSTONE: self._apply_tombstone,
            MessageType.COORDINATE_BATCH_UPDATE: self._apply_coordinates,
            MessageType.PREPARED_QUERY: self._apply_prepared_query,
            MessageType.TXN: self._apply_txn,
            MessageType.AUTOPILOT: self._apply_autopilot,
            MessageType.ACL_TOKEN_SET: self._apply_acl_token_set,
            MessageType.ACL_TOKEN_DELETE: self._apply_acl_token_delete,
            MessageType.ACL_POLICY_SET: self._apply_acl_policy_set,
            MessageType.ACL_POLICY_DELETE: self._apply_acl_policy_delete,
            MessageType.CONFIG_ENTRY: self._apply_config_entry,
        }

    # -- raft.FSM interface -------------------------------------------------

    def apply(self, entry: Entry) -> Any:
        msg_type = int(entry.data["type"])
        body = entry.data.get("body", {})
        handler = self._handlers.get(msg_type & ~IGNORE_UNKNOWN_FLAG)
        if handler is None:
            if msg_type & IGNORE_UNKNOWN_FLAG:
                log.warning("ignoring unknown message type %d", msg_type)
                return None
            raise ValueError(f"unknown raft command type {msg_type}")
        try:
            return handler(entry.index, body)
        except (ValueError, KeyError, TypeError) as e:
            # Domain errors (bad registration, missing session, malformed
            # body...) are a *result*, not an FSM failure: every replica
            # deterministically computes the same error and the leader
            # returns it to the caller (the reference returns the error
            # as the Apply value).
            return {"error": f"{type(e).__name__}: {e}"}

    def snapshot(self) -> Any:
        return self.store.snapshot()

    def restore(self, snap: Any) -> None:
        # The reference builds a NEW state store and abandons the old
        # one so blocked queries wake and re-run (fsm.go Restore);
        # StateStore.restore does both.
        self.store.restore(snap)

    # -- command handlers (fsm/commands_oss.go) -----------------------------

    def _apply_register(self, idx: int, body: dict) -> Any:
        self.store.ensure_registration(idx, body)
        return True

    def _apply_deregister(self, idx: int, body: dict) -> Any:
        # Precedence mirrors applyDeregister: a service or check id
        # limits the deregistration; otherwise the whole node goes.
        node = body["node"]
        if body.get("service_id"):
            return self.store.delete_service(idx, node, body["service_id"])
        if body.get("check_id"):
            return self.store.delete_check(idx, node, body["check_id"])
        return self.store.delete_node(idx, node)

    def _apply_kvs(self, idx: int, body: dict) -> Any:
        op = body["op"]
        entry = body.get("entry") or {}
        s = self.store
        if op == "set":
            s.kv_set(idx, entry)
            return True
        if op == "cas":
            return s.kv_set_cas(idx, entry, int(entry.get("modify_index", 0)))
        if op == "delete":
            return s.kv_delete(idx, entry["key"])
        if op == "delete-cas":
            return s.kv_delete_cas(idx, entry["key"], int(entry.get("modify_index", 0)))
        if op == "delete-tree":
            return s.kv_delete_tree(idx, entry["key"])
        if op == "lock":
            return s.kv_lock(idx, entry, entry.get("session") or "")
        if op == "unlock":
            return s.kv_unlock(idx, entry, entry.get("session") or "")
        raise ValueError(f"invalid KVS operation {op!r}")

    def _apply_session(self, idx: int, body: dict) -> Any:
        op = body["op"]
        if op == "create":
            self.store.session_create(idx, body["session"])
            return body["session"]["id"]
        if op == "destroy":
            return self.store.session_destroy(idx, body["session"]["id"])
        raise ValueError(f"invalid session operation {op!r}")

    def _apply_tombstone(self, idx: int, body: dict) -> Any:
        if body.get("op") != "reap":
            raise ValueError(f"invalid tombstone operation {body.get('op')!r}")
        return self.store.tombstone_reap(idx, int(body["index"]))

    def _apply_coordinates(self, idx: int, body: dict) -> Any:
        self.store.coordinate_batch_update(idx, body["updates"])
        return True

    def _apply_prepared_query(self, idx: int, body: dict) -> Any:
        op = body["op"]
        if op in ("create", "update"):
            self.store.prepared_query_set(idx, body["query"])
            return body["query"]["id"]
        if op == "delete":
            return self.store.prepared_query_delete(idx, body["query"]["id"])
        raise ValueError(f"invalid prepared query operation {op!r}")

    def _apply_txn(self, idx: int, body: dict) -> Any:
        results, errors = self.store.txn_apply(idx, body["ops"])
        return {"results": results, "errors": errors}

    def _apply_autopilot(self, idx: int, body: dict) -> Any:
        # Stored as a config entry of a reserved kind (the reference has
        # a dedicated autopilot-config table; one-row table ≡ one entry).
        cfg = dict(body["config"])
        cfg["kind"] = "autopilot-config"
        cfg["name"] = "global"
        if body.get("cas"):
            existing = self.store.config_entry_get("autopilot-config", "global")[1]
            have = existing["modify_index"] if existing else 0
            if have != int(body.get("modify_index", 0)):
                return False
        self.store.config_entry_set(idx, cfg)
        return True

    def _apply_acl_token_set(self, idx: int, body: dict) -> Any:
        self.store.acl_token_set(idx, body["token"])
        return True

    def _apply_acl_token_delete(self, idx: int, body: dict) -> Any:
        return self.store.acl_token_delete(idx, body["secret_id"])

    def _apply_acl_policy_set(self, idx: int, body: dict) -> Any:
        self.store.acl_policy_set(idx, body["policy"])
        return True

    def _apply_acl_policy_delete(self, idx: int, body: dict) -> Any:
        return self.store.acl_policy_delete(idx, body["id"])

    def _apply_config_entry(self, idx: int, body: dict) -> Any:
        op = body["op"]
        entry = body.get("entry") or {}
        if op in ("set", "upsert"):
            self.store.config_entry_set(idx, entry)
            return True
        if op == "cas":
            existing = self.store.config_entry_get(entry["kind"], entry["name"])[1]
            have = existing["modify_index"] if existing else 0
            if have != int(body.get("modify_index", 0)):
                return False
            self.store.config_entry_set(idx, entry)
            return True
        if op == "delete":
            return self.store.config_entry_delete(idx, entry["kind"], entry["name"])
        raise ValueError(f"invalid config entry operation {op!r}")
