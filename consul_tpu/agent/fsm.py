"""The replicated state machine: raft log entries → StateStore writes.

Equivalent of the reference's ``agent/consul/fsm`` package: a dispatch
table from message type to a command handler built at init
(``fsm/fsm.go:19-120``), the command handlers themselves
(``fsm/commands_oss.go:13-40``), and whole-store snapshot/restore
(``fsm/snapshot_oss.go``).

Raft entry payloads are ``{"type": MessageType, "body": {...}}`` dicts
(the reference encodes the type as the first byte of the msgpack buffer,
``structs.Encode``); bodies are msgpack-friendly dicts throughout.

A message type OR'd with ``IGNORE_UNKNOWN_FLAG`` (bit 7) is skipped
without error when this node doesn't understand it — the reference's
forward-compatibility rule (``structs/structs.go`` IgnoreUnknownTypeFlag).
"""

from __future__ import annotations

import enum
import logging
import time
from typing import Any, Callable, Optional

from consul_tpu.consensus.raft import FSM, Entry
from consul_tpu.store.state import StateStore
from consul_tpu.telemetry import metrics
from consul_tpu.stream import (
    TOPIC_KV,
    TOPIC_SERVICE_HEALTH,
    Event,
    EventPublisher,
)

log = logging.getLogger("consul_tpu.fsm")

IGNORE_UNKNOWN_FLAG = 128  # structs/structs.go IgnoreUnknownTypeFlag


class MessageType(enum.IntEnum):
    """Raft command types (``agent/structs/structs.go`` MessageType
    consts; same numbering so snapshots stay comparable)."""

    REGISTER = 0
    DEREGISTER = 1
    KVS = 2
    SESSION = 3
    ACL = 4  # deprecated legacy ACL path (unused, reserved)
    TOMBSTONE = 5
    COORDINATE_BATCH_UPDATE = 6
    PREPARED_QUERY = 7
    TXN = 8
    AUTOPILOT = 9
    AREA = 10
    ACL_BOOTSTRAP = 11
    INTENTION = 12
    CONNECT_CA = 13
    ACL_TOKEN_SET = 17
    ACL_TOKEN_DELETE = 18
    ACL_POLICY_SET = 19
    ACL_POLICY_DELETE = 20
    CONFIG_ENTRY = 22
    ACL_ROLE_SET = 23
    ACL_ROLE_DELETE = 24
    ACL_BINDING_RULE_SET = 25
    ACL_BINDING_RULE_DELETE = 26
    ACL_AUTH_METHOD_SET = 27
    ACL_AUTH_METHOD_DELETE = 28
    FEDERATION_STATE = 30
    # Not a reference command type: the reference installs user-snapshot
    # restores through raft.Restore/InstallSnapshot; here the unpacked
    # state rides one replicated log entry instead (agent/snapshot.py).
    SNAPSHOT_RESTORE = 96


_METRIC_NAMES = {
    int(t): f"consul.fsm.{t.name.lower()}" for t in MessageType
}


class ConsulFSM(FSM):
    """Applies committed raft entries to a :class:`StateStore`.

    The FSM is the ONLY writer to the store on a server, so every read
    is a consistent snapshot at some raft index (``fsm/fsm.go:102``).
    """

    def __init__(
        self,
        store: Optional[StateStore] = None,
        publisher: Optional[EventPublisher] = None,
    ):
        self.store = store or StateStore()
        # Change-stream publisher (state/memdb.go:37-41 wires the
        # reference's changeTrackerDB to the EventPublisher; here the
        # FSM is the single writer, so it is the publish point).
        self.publisher = publisher
        if publisher is not None:
            publisher.register_snapshot_handler(
                TOPIC_SERVICE_HEALTH, self._snapshot_service_health
            )
            publisher.register_snapshot_handler(TOPIC_KV, self._snapshot_kv)
        self._handlers: dict[int, Callable[[int, dict], Any]] = {
            MessageType.REGISTER: self._apply_register,
            MessageType.DEREGISTER: self._apply_deregister,
            MessageType.KVS: self._apply_kvs,
            MessageType.SESSION: self._apply_session,
            MessageType.TOMBSTONE: self._apply_tombstone,
            MessageType.COORDINATE_BATCH_UPDATE: self._apply_coordinates,
            MessageType.PREPARED_QUERY: self._apply_prepared_query,
            MessageType.TXN: self._apply_txn,
            MessageType.AUTOPILOT: self._apply_autopilot,
            MessageType.INTENTION: self._apply_intention,
            MessageType.CONNECT_CA: self._apply_connect_ca,
            MessageType.SNAPSHOT_RESTORE: self._apply_snapshot_restore,
            MessageType.ACL_TOKEN_SET: self._apply_acl_token_set,
            MessageType.ACL_TOKEN_DELETE: self._apply_acl_token_delete,
            MessageType.ACL_POLICY_SET: self._apply_acl_policy_set,
            MessageType.ACL_POLICY_DELETE: self._apply_acl_policy_delete,
            MessageType.ACL_ROLE_SET: self._apply_acl_role_set,
            MessageType.ACL_ROLE_DELETE: self._apply_acl_role_delete,
            MessageType.ACL_BINDING_RULE_SET:
                self._apply_acl_binding_rule_set,
            MessageType.ACL_BINDING_RULE_DELETE:
                self._apply_acl_binding_rule_delete,
            MessageType.ACL_AUTH_METHOD_SET:
                self._apply_acl_auth_method_set,
            MessageType.ACL_AUTH_METHOD_DELETE:
                self._apply_acl_auth_method_delete,
            MessageType.CONFIG_ENTRY: self._apply_config_entry,
            MessageType.FEDERATION_STATE: self._apply_federation_state,
        }

    # -- raft.FSM interface -------------------------------------------------

    def apply(self, entry: Entry) -> Any:
        msg_type = int(entry.data["type"])
        body = entry.data.get("body", {})
        handler = self._handlers.get(msg_type & ~IGNORE_UNKNOWN_FLAG)
        if handler is None:
            if msg_type & IGNORE_UNKNOWN_FLAG:
                log.warning("ignoring unknown message type %d", msg_type)
                return None
            raise ValueError(f"unknown raft command type {msg_type}")
        pre = (
            self._pre_change_info(msg_type & ~IGNORE_UNKNOWN_FLAG, body)
            if self.publisher is not None
            else None
        )
        try:
            _t0 = time.monotonic()
            result = handler(entry.index, body)
            metrics().measure_since(
                _METRIC_NAMES[msg_type & ~IGNORE_UNKNOWN_FLAG], _t0
            )
        except (ValueError, KeyError, TypeError) as e:
            # Domain errors (bad registration, missing session, malformed
            # body...) are a *result*, not an FSM failure: every replica
            # deterministically computes the same error and the leader
            # returns it to the caller (the reference returns the error
            # as the Apply value).
            return {"error": f"{type(e).__name__}: {e}"}
        if self.publisher is not None:
            try:
                events = self._events_for(
                    msg_type & ~IGNORE_UNKNOWN_FLAG, entry.index, body, pre
                )
                if events:
                    self.publisher.publish(events)
            except Exception:  # noqa: BLE001 - stream must never fail raft
                log.exception("event publish failed")
        return result

    def snapshot(self) -> Any:
        return self.store.snapshot()

    def restore(self, snap: Any) -> None:
        # The reference builds a NEW state store and abandons the old
        # one so blocked queries wake and re-run (fsm.go Restore);
        # StateStore.restore does both.  Stream subscribers likewise get
        # force-closed and must resubscribe for a fresh snapshot
        # (event_publisher.go on index regression).
        self.store.restore(snap)
        if self.publisher is not None:
            self.publisher.close_all()

    # -- change-stream plumbing (state/memdb.go:37-41 equivalents) ----------

    def _snapshot_service_health(self, key: str) -> tuple[int, list]:
        idx, rows = self.store.check_service_nodes(key)
        return idx, [
            Event(topic=TOPIC_SERVICE_HEALTH, key=key, index=idx, payload=rows)
        ]

    def _snapshot_kv(self, prefix: str) -> tuple[int, list]:
        idx, entries = self.store.kv_list(prefix)
        return idx, [
            Event(topic=TOPIC_KV, key=e["key"], index=idx, payload=e)
            for e in entries
        ]

    def _node_service_names(self, node: str) -> set[str]:
        try:
            _, services = self.store.node_services(node)
        except Exception:  # noqa: BLE001 - node may be gone
            return set()
        return {s.get("service", s.get("id", "")) for s in services}

    def _pre_change_info(self, msg_type: int, body: dict) -> Optional[dict]:
        """Subjects only determinable BEFORE the store mutates (a
        deregistration or recursive delete removes the rows we need to
        look at): affected service names and kv keys."""
        if msg_type == MessageType.DEREGISTER:
            node = body.get("node", "")
            if body.get("service_id"):
                names = set()
                _, services = self.store.node_services(node)
                for s in services:
                    if s.get("id") == body["service_id"]:
                        names.add(s.get("service", ""))
                return {"services": names}
            return {"services": self._node_service_names(node)}
        if msg_type == MessageType.KVS and body.get("op") == "delete-tree":
            prefix = (body.get("entry") or {}).get("key", "")
            _, entries = self.store.kv_list(prefix)
            return {"kv_keys": {e["key"] for e in entries}}
        return None

    def _events_for(
        self, msg_type: int, idx: int, body: dict, pre: Optional[dict]
    ) -> list:
        services: set[str] = set(
            (pre or {}).get("services", ())
        )
        kv_keys: set[str] = set((pre or {}).get("kv_keys", ()))
        if msg_type == MessageType.REGISTER:
            svc = body.get("service")
            if svc:
                services.add(svc.get("service", svc.get("id", "")))
            checks = list(body.get("checks") or [])
            if body.get("check"):
                checks.append(body["check"])
            for c in checks:
                if c.get("service_id"):
                    # Map the check's service id to its name.
                    node = body.get("node", "")
                    _, node_svcs = self.store.node_services(node)
                    for s in node_svcs:
                        if s.get("id") == c["service_id"]:
                            services.add(s.get("service", ""))
                else:
                    # Node-level check affects every service on the node
                    # (a failing serf check fails them all).
                    services |= self._node_service_names(body.get("node", ""))
            if not svc and not checks:
                # Node-only update (e.g. address change): every service
                # on the node embeds the node record in its rows.
                services |= self._node_service_names(body.get("node", ""))
        elif msg_type == MessageType.KVS:
            entry = body.get("entry") or {}
            if entry.get("key"):
                kv_keys.add(entry["key"])
        elif msg_type == MessageType.TXN:
            for op in body.get("ops", []):
                entry = (op.get("kv") or {}).get("entry") or {}
                if entry.get("key"):
                    kv_keys.add(entry["key"])
        events: list = []
        for name in sorted(s for s in services if s):
            _, rows = self.store.check_service_nodes(name)
            events.append(
                Event(
                    topic=TOPIC_SERVICE_HEALTH, key=name, index=idx,
                    payload=rows,
                )
            )
        for key in sorted(kv_keys):
            _, entry = self.store.kv_get(key)
            events.append(
                Event(topic=TOPIC_KV, key=key, index=idx, payload=entry)
            )
        return events

    # -- command handlers (fsm/commands_oss.go) -----------------------------

    def _apply_register(self, idx: int, body: dict) -> Any:
        self.store.ensure_registration(idx, body)
        return True

    def _apply_deregister(self, idx: int, body: dict) -> Any:
        # Precedence mirrors applyDeregister: a service or check id
        # limits the deregistration; otherwise the whole node goes.
        node = body["node"]
        if body.get("service_id"):
            return self.store.delete_service(idx, node, body["service_id"])
        if body.get("check_id"):
            return self.store.delete_check(idx, node, body["check_id"])
        return self.store.delete_node(idx, node)

    def _apply_kvs(self, idx: int, body: dict) -> Any:
        op = body["op"]
        entry = body.get("entry") or {}
        s = self.store
        if op == "set":
            s.kv_set(idx, entry)
            return True
        if op == "cas":
            return s.kv_set_cas(idx, entry, int(entry.get("modify_index", 0)))
        if op == "delete":
            return s.kv_delete(idx, entry["key"])
        if op == "delete-cas":
            return s.kv_delete_cas(idx, entry["key"], int(entry.get("modify_index", 0)))
        if op == "delete-tree":
            return s.kv_delete_tree(idx, entry["key"])
        if op == "lock":
            return s.kv_lock(idx, entry, entry.get("session") or "")
        if op == "unlock":
            return s.kv_unlock(idx, entry, entry.get("session") or "")
        raise ValueError(f"invalid KVS operation {op!r}")

    def _apply_session(self, idx: int, body: dict) -> Any:
        op = body["op"]
        if op == "create":
            self.store.session_create(idx, body["session"])
            return body["session"]["id"]
        if op == "destroy":
            return self.store.session_destroy(idx, body["session"]["id"])
        raise ValueError(f"invalid session operation {op!r}")

    def _apply_tombstone(self, idx: int, body: dict) -> Any:
        if body.get("op") != "reap":
            raise ValueError(f"invalid tombstone operation {body.get('op')!r}")
        return self.store.tombstone_reap(idx, int(body["index"]))

    def _apply_coordinates(self, idx: int, body: dict) -> Any:
        self.store.coordinate_batch_update(idx, body["updates"])
        return True

    def _apply_prepared_query(self, idx: int, body: dict) -> Any:
        op = body["op"]
        if op in ("create", "update"):
            self.store.prepared_query_set(idx, body["query"])
            return body["query"]["id"]
        if op == "delete":
            return self.store.prepared_query_delete(idx, body["query"]["id"])
        raise ValueError(f"invalid prepared query operation {op!r}")

    def _apply_txn(self, idx: int, body: dict) -> Any:
        results, errors = self.store.txn_apply(idx, body["ops"])
        return {"results": results, "errors": errors}

    def _apply_autopilot(self, idx: int, body: dict) -> Any:
        # Stored as a config entry of a reserved kind (the reference has
        # a dedicated autopilot-config table; one-row table ≡ one entry).
        cfg = dict(body["config"])
        cfg["kind"] = "autopilot-config"
        cfg["name"] = "global"
        if body.get("cas"):
            existing = self.store.config_entry_get("autopilot-config", "global")[1]
            have = existing["modify_index"] if existing else 0
            if have != int(body.get("modify_index", 0)):
                return False
        self.store.config_entry_set(idx, cfg)
        return True

    def _apply_intention(self, idx: int, body: dict) -> Any:
        """fsm intention ops (commands_oss.go applyIntentionOperation)."""
        op = body["op"]
        if op in ("create", "update"):
            self.store.intention_set(idx, body["intention"])
            return body["intention"]["id"]
        if op == "delete":
            return self.store.intention_delete(idx, body["intention"]["id"])
        raise ValueError(f"invalid intention operation {op!r}")

    def _apply_connect_ca(self, idx: int, body: dict) -> Any:
        """CA root records replicated through raft (connect_ca ops)."""
        if body.get("op") == "set-root":
            self.store.ca_root_set(idx, body["root"])
            return True
        raise ValueError(f"invalid connect-ca operation {body.get('op')!r}")

    def _apply_snapshot_restore(self, idx: int, body: dict) -> Any:
        """Install a user snapshot on every replica at the same log
        position (snapshot_endpoint.go Restore -> raft.Restore)."""
        self.restore(body["state"])
        return True

    def _apply_acl_token_set(self, idx: int, body: dict) -> Any:
        self.store.acl_token_set(idx, body["token"])
        return True

    def _apply_acl_token_delete(self, idx: int, body: dict) -> Any:
        return self.store.acl_token_delete(idx, body["secret_id"])

    def _apply_acl_policy_set(self, idx: int, body: dict) -> Any:
        self.store.acl_policy_set(idx, body["policy"])
        return True

    def _apply_acl_policy_delete(self, idx: int, body: dict) -> Any:
        return self.store.acl_policy_delete(idx, body["id"])

    def _apply_acl_role_set(self, idx: int, body: dict) -> Any:
        self.store.acl_role_set(idx, body["role"])
        return True

    def _apply_acl_role_delete(self, idx: int, body: dict) -> Any:
        return self.store.acl_role_delete(idx, body["id"])

    def _apply_acl_binding_rule_set(self, idx: int, body: dict) -> Any:
        self.store.acl_binding_rule_set(idx, body["rule"])
        return True

    def _apply_acl_binding_rule_delete(self, idx: int, body: dict) -> Any:
        return self.store.acl_binding_rule_delete(idx, body["id"])

    def _apply_acl_auth_method_set(self, idx: int, body: dict) -> Any:
        self.store.acl_auth_method_set(idx, body["method"])
        return True

    def _apply_acl_auth_method_delete(self, idx: int, body: dict) -> Any:
        return self.store.acl_auth_method_delete(idx, body["name"])

    def _apply_federation_state(self, idx: int, body: dict) -> Any:
        """fsm/commands_oss.go applyFederationStateOperation."""
        op = body["op"]
        state = body.get("state") or {}
        if not state.get("datacenter"):
            raise ValueError("federation state must name a datacenter")
        if op == "upsert":
            self.store.federation_state_set(idx, state)
            return True
        if op == "delete":
            return self.store.federation_state_delete(
                idx, state["datacenter"]
            )
        raise ValueError(f"invalid federation state operation {op!r}")

    def _apply_config_entry(self, idx: int, body: dict) -> Any:
        op = body["op"]
        entry = body.get("entry") or {}
        if op in ("set", "upsert"):
            self.store.config_entry_set(idx, entry)
            return True
        if op == "cas":
            existing = self.store.config_entry_get(entry["kind"], entry["name"])[1]
            have = existing["modify_index"] if existing else 0
            if have != int(body.get("modify_index", 0)):
                return False
            self.store.config_entry_set(idx, entry)
            return True
        if op == "delete":
            return self.store.config_entry_delete(idx, entry["kind"], entry["name"])
        raise ValueError(f"invalid config entry operation {op!r}")
