"""Agent plane: the consistency-plane node logic above the gossip layer.

Equivalent of the reference's ``agent/`` + ``agent/consul/`` packages
(SURVEY.md §2.2-2.3, layers L3-L6): FSM, RPC plumbing with blocking
queries, Server (raft quorum member) and Client (RPC-forwarding thin
agent), and the composition-root Agent with HTTP/DNS front ends.
"""

from consul_tpu.agent.agent import Agent, AgentConfig
from consul_tpu.agent.client import Client, ClientConfig
from consul_tpu.agent.fsm import ConsulFSM, MessageType
from consul_tpu.agent.server import Server, ServerConfig

__all__ = [
    "Agent",
    "AgentConfig",
    "Client",
    "ClientConfig",
    "ConsulFSM",
    "MessageType",
    "Server",
    "ServerConfig",
]
