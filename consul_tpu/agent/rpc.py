"""RPC plumbing: multiplexed msgpack RPC over Transport streams.

Equivalent of the reference's server RPC stack (SURVEY.md §2.2):

  first-byte conn mux     pool/conn.go:30-43 — a new stream's first
                          frame is one type byte selecting the protocol
                          (Consul RPC, Raft, Snapshot); everything
                          shares one listener
  multiplexed RPC         agent/pool/pool.go (yamux) — here one
                          persistent stream per peer carries
                          concurrent ``{seq, method, body}`` request
                          frames and ``{seq, error, body}`` responses
  dispatch                rpc.go:360 handleConsulConn → net/rpc-style
                          ``Service.Method`` names resolved against
                          registered endpoint objects
                          (server_oss.go:8-23)
  blocking queries        rpc.go:759-861 blockingQuery — memdb
                          WatchSet long-poll with jittered timeout and
                          index sanity rules

Method names keep the reference's Go spelling (``KVS.Apply``,
``Health.ServiceNodes``) and are resolved to snake_case coroutine
methods on the endpoint object, so the wire surface matches the
reference while the code stays Pythonic.
"""

from __future__ import annotations

import asyncio
import dataclasses
import inspect
import logging
import random
import re
import time
from typing import Any, AsyncIterator, Callable, Optional

import msgpack

from consul_tpu.net.transport import Stream, Transport
from consul_tpu.telemetry import metrics
from consul_tpu.store.memdb import WatchSet
from consul_tpu.store.state import StateStore

log = logging.getLogger("consul_tpu.rpc")

# Stream type bytes (pool/conn.go:30-43; gossip/TLS slots reserved).
RPC_CONSUL = 0
RPC_RAFT = 1
RPC_MULTIPLEX_V2 = 4
RPC_SNAPSHOT = 5

# Blocking query timing (rpc.go / config.go).
DEFAULT_QUERY_TIME = 300.0  # DefaultQueryTime  (5 min)
MAX_QUERY_TIME = 600.0  # MaxQueryTime (10 min)
JITTER_FRACTION = 16  # lib.RandomStagger denominator (rpc.go:788)


class RPCError(Exception):
    """Remote error string surfaced to the caller (net/rpc ServerError)."""


ERR_NO_LEADER = "No cluster leader"  # structs.ErrNoLeader
ERR_PERMISSION_DENIED = "Permission denied"  # acl.ErrPermissionDenied
ERR_ACL_NOT_FOUND = "ACL not found"  # acl.ErrNotFound


def _pack(obj: Any) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def _unpack(raw: bytes) -> Any:
    return msgpack.unpackb(raw, raw=False, strict_map_key=False)


_CAMEL_RE = re.compile(r"(?<=[a-z0-9])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])")


# Flow control (the yamux window analogue, in frames not bytes):
# per-streaming-call credit window and the shared write queue's bound.
STREAM_WINDOW = 32
SESSION_WINDOW = 1024


def snake(name: str) -> str:
    """``ServiceNodes`` → ``service_nodes`` (wire name → method name)."""
    return _CAMEL_RE.sub("_", name).lower()


def rpc_timeout_for(body: dict, default: float = 30.0) -> float:
    """Client-side wait budget for a call that may long-poll server-side
    (pool.go RPC deadline = query wait + jitter + RPCHoldTimeout): a
    blocking query must be given its full max_query_time plus the
    server's jitter (1/16) and a grace window, or a follower/client
    forwarding it would time out before the leader answers."""
    if int(body.get("min_query_index", 0) or 0) <= 0:
        return default
    wait = float(body.get("max_query_time", 0.0) or 0.0) or DEFAULT_QUERY_TIME
    wait = min(wait, MAX_QUERY_TIME)
    return wait + wait / JITTER_FRACTION + 5.0


@dataclasses.dataclass
class QueryOptions:
    """Client-supplied read options (structs.QueryOptions)."""

    min_query_index: int = 0
    max_query_time: float = 0.0  # 0 → DefaultQueryTime
    allow_stale: bool = False
    require_consistent: bool = False
    token: str = ""

    @classmethod
    def from_body(cls, body: dict) -> "QueryOptions":
        return cls(
            min_query_index=int(body.get("min_query_index", 0)),
            max_query_time=float(body.get("max_query_time", 0.0)),
            allow_stale=bool(body.get("allow_stale", False)),
            require_consistent=bool(body.get("require_consistent", False)),
            token=body.get("token", ""),
        )


@dataclasses.dataclass
class QueryMeta:
    """Server-reported read metadata (structs.QueryMeta →
    X-Consul-Index / X-Consul-KnownLeader / X-Consul-LastContact)."""

    index: int = 0
    known_leader: bool = True
    last_contact: float = 0.0

    def to_body(self) -> dict:
        return {
            "index": self.index,
            "known_leader": self.known_leader,
            "last_contact": self.last_contact,
        }


async def blocking_query(
    store: StateStore,
    opts: QueryOptions,
    run: Callable[[Optional[WatchSet]], tuple[int, Any]],
    *,
    rng: Optional[random.Random] = None,
) -> tuple[QueryMeta, Any]:
    """The long-poll loop of ``rpc.go:759-861 blockingQuery``.

    ``run(ws)`` executes the read against the store, registering radix
    watches on ``ws``, and returns ``(index, result)``.  Semantics kept
    from the reference: not blocking when min_query_index is 0; wait
    capped to MaxQueryTime with +1/16 jitter; a returned index below 1
    is reported as 1; an index that went *backwards* past the client's
    is served immediately (index sanity, rpc.go:836-848).
    """
    meta = QueryMeta()
    if opts.min_query_index <= 0:
        index, result = run(None)
        meta.index = max(index, 1)
        return meta, result

    wait = opts.max_query_time or DEFAULT_QUERY_TIME
    wait = min(wait, MAX_QUERY_TIME)
    wait += (rng or random).random() * wait / JITTER_FRACTION
    deadline = time.monotonic() + wait
    # rpc.go:796 metrics.IncrCounter rpc.queries_blocking.
    metrics().incr_counter("rpc.queries_blocking")

    while True:
        ws = WatchSet()
        abandon = store.abandon_event()
        ws.add(abandon)
        index, result = run(ws)
        if index < 1:
            index = 1
        if index < opts.min_query_index:
            # Store was reset (snapshot restore): serve immediately so
            # the client restarts its watch from the new world.
            meta.index = index
            return meta, result
        if index > opts.min_query_index:
            meta.index = index
            return meta, result
        remaining = deadline - time.monotonic()
        fired = remaining > 0 and await ws.wait(remaining)
        if abandon.is_set():
            # Store swapped out from under us (snapshot restore): return
            # right away so the client re-queries the new store
            # (rpc.go:825 AbandonCh case).
            meta.index = index
            return meta, result
        if not fired:
            meta.index = index
            return meta, result


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------


class RPCServer:
    """Accepts streams from a Transport, muxes by first byte, serves
    Consul RPC frames (rpc.go:61-360 listen/handleConn)."""

    def __init__(self, transport: Transport):
        self.transport = transport
        self._endpoints: dict[str, Any] = {}
        self._raft_handler: Optional[Callable] = None
        self._snapshot_handler: Optional[Callable] = None
        self._tasks: list[asyncio.Task] = []
        self._conn_tasks: set[asyncio.Task] = set()
        self._shutdown = False

    def register(self, name: str, endpoint: Any) -> None:
        """Register an endpoint service (server_oss.go:8-23)."""
        self._endpoints[name] = endpoint

    def bind_raft(self, handler: Callable) -> None:
        """handler(method: str, body: dict) -> dict, from the raft node."""
        self._raft_handler = handler

    def bind_snapshot(self, handler: Callable) -> None:
        """handler(stream, body) for streaming snapshot save/restore."""
        self._snapshot_handler = handler

    async def start(self) -> None:
        self._tasks.append(asyncio.create_task(self._accept_loop()))

    async def shutdown(self) -> None:
        self._shutdown = True
        for t in self._tasks + list(self._conn_tasks):
            t.cancel()

    async def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                stream = await self.transport.accept_stream()
            except (asyncio.CancelledError, ConnectionError):
                return
            t = asyncio.create_task(self._handle_conn(stream))
            self._conn_tasks.add(t)
            t.add_done_callback(self._conn_tasks.discard)

    async def _handle_conn(self, stream: Stream) -> None:
        try:
            first = await stream.recv(timeout=30.0)
        except (asyncio.TimeoutError, ConnectionError, asyncio.CancelledError):
            await stream.close()
            return
        rpc_type = first[0] if first else -1
        try:
            if rpc_type in (RPC_CONSUL, RPC_MULTIPLEX_V2):
                await self._serve_frames(stream, self._dispatch_consul)
            elif rpc_type == RPC_RAFT:
                await self._serve_frames(stream, self._dispatch_raft)
            elif rpc_type == RPC_SNAPSHOT and self._snapshot_handler:
                await self._snapshot_handler(stream)
            else:
                log.warning("unrecognized RPC byte %r; closing", rpc_type)
        except (ConnectionError, asyncio.CancelledError, asyncio.TimeoutError):
            pass
        finally:
            await stream.close()

    async def _serve_frames(self, stream: Stream, dispatch: Callable) -> None:
        """Request pump: decode frames, run each in its own task, write
        responses through a queue (so concurrent handlers never
        interleave partial writes — the yamux-per-stream analogue).

        Flow control (yamux session/stream windows, yamux/session.go +
        stream.go): the shared write queue is BOUNDED (session-level
        backpressure — a slow socket suspends handlers instead of
        buffering without limit), and each server-streaming call holds a
        credit window of STREAM_WINDOW frames — the producer blocks when
        the client stops consuming, and the client grants more credit
        as its application drains (window-update frames)."""
        write_q: asyncio.Queue = asyncio.Queue(maxsize=SESSION_WINDOW)
        pending: set[asyncio.Task] = set()
        streams_by_seq: dict[int, asyncio.Task] = {}
        stream_credits: dict[int, asyncio.Semaphore] = {}
        # Cancels that raced ahead of their handler task starting.
        cancelled_seqs: set[int] = set()

        async def writer():
            try:
                while True:
                    frame = await write_q.get()
                    await stream.send(frame)
            except (ConnectionError, asyncio.CancelledError):
                pass
            finally:
                # A dead writer means responses can never be delivered:
                # close the stream so the request loop's recv unblocks
                # and the whole conn tears down instead of queueing
                # responses into the void.
                await stream.close()

        wtask = asyncio.create_task(writer())
        try:
            while True:
                raw = await stream.recv()
                req = _unpack(raw)
                if req.get("credit"):
                    # Window update: the client consumed k frames.
                    sem = stream_credits.get(req.get("seq", 0))
                    if sem is not None:
                        for _ in range(int(req["credit"])):
                            sem.release()
                    continue
                if req.get("cancel"):
                    # Client abandoned a server-streaming call
                    # (grpc-style cancellation for Subscribe).  The
                    # handler task may not have started yet — remember
                    # the seq so it aborts on arrival.
                    seq = req.get("seq", 0)
                    t = streams_by_seq.pop(seq, None)
                    if t is not None:
                        t.cancel()
                    else:
                        cancelled_seqs.add(seq)
                        # Seqs are monotonic per connection: entries far
                        # behind the current seq belong to streams that
                        # already finished — drop them so a cancel that
                        # raced a normal completion can't accumulate.
                        if len(cancelled_seqs) > 64:
                            cancelled_seqs.intersection_update(
                                s for s in cancelled_seqs if s > seq - 512
                            )
                    continue

                async def handle(req=req):
                    seq = req.get("seq", 0)
                    try:
                        result = await dispatch(req["method"], req.get("body") or {})
                        if inspect.isasyncgen(result):
                            if seq in cancelled_seqs:
                                # Cancel frame beat us here.
                                cancelled_seqs.discard(seq)
                                await result.aclose()
                                return
                            # Server-streaming response (the gRPC
                            # subscribe analogue, subscribe.go:45): one
                            # frame per yielded item with more=True,
                            # then a closing frame.
                            streams_by_seq[seq] = asyncio.current_task()
                            credit = asyncio.Semaphore(STREAM_WINDOW)
                            stream_credits[seq] = credit
                            try:
                                async for item in result:
                                    # One credit per frame: blocks here
                                    # when the client stops consuming.
                                    await credit.acquire()
                                    await write_q.put(_pack(
                                        {"seq": seq, "error": None,
                                         "body": item, "more": True}
                                    ))
                                resp = {"seq": seq, "error": None,
                                        "body": None, "more": False}
                            except asyncio.CancelledError:
                                await result.aclose()
                                return
                            finally:
                                streams_by_seq.pop(seq, None)
                                stream_credits.pop(seq, None)
                        else:
                            resp = {"seq": seq, "error": None, "body": result}
                    except Exception as e:  # noqa: BLE001 — error -> wire
                        resp = {"seq": seq, "error": str(e) or repr(e), "body": None}
                    try:
                        frame = _pack(resp)
                    except Exception as e:  # unserializable result
                        frame = _pack(
                            {"seq": seq, "error": f"unserializable response: {e}",
                             "body": None}
                        )
                    await write_q.put(frame)

                t = asyncio.create_task(handle())
                pending.add(t)
                t.add_done_callback(pending.discard)
        finally:
            wtask.cancel()
            for t in pending:
                t.cancel()

    async def dispatch_local(self, method: str, body: dict) -> Any:
        """In-process dispatch to a registered endpoint — the server
        agent's own RPC entry point (no wire round-trip)."""
        return await self._dispatch_consul(method, body)

    async def _dispatch_consul(self, method: str, body: dict) -> Any:
        service, _, verb = method.partition(".")
        endpoint = self._endpoints.get(service)
        fn = getattr(endpoint, snake(verb), None) if endpoint else None
        if fn is None or verb.startswith("_"):
            raise RPCError(f"rpc: can't find method {method}")
        if inspect.isasyncgenfunction(fn):
            # Server-streaming endpoint: hand the generator back to the
            # frame pump (or a local caller) to iterate.
            return fn(body)
        return await fn(body)

    async def _dispatch_raft(self, method: str, body: dict) -> Any:
        if self._raft_handler is None:
            raise RPCError("raft not enabled on this node")
        return await self._raft_handler(method, body)


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------


class _Conn:
    """One persistent muxed stream to a peer (agent/pool ConnPool entry)."""

    def __init__(self, stream: Stream):
        self.stream = stream
        self.seq = 0
        self.waiters: dict[int, asyncio.Future] = {}
        # seq -> queue for server-streaming calls (multiple frames).
        self.stream_waiters: dict[int, asyncio.Queue] = {}
        self.reader: Optional[asyncio.Task] = None
        self.dead = False

    def fail_all(self, exc: Exception) -> None:
        self.dead = True
        for fut in self.waiters.values():
            if not fut.done():
                fut.set_exception(exc)
        self.waiters.clear()
        for q in self.stream_waiters.values():
            q.put_nowait(exc)
        self.stream_waiters.clear()


class RPCClient:
    """Connection-pooled msgpack RPC caller (agent/pool/pool.go)."""

    def __init__(self, transport: Transport, rpc_type: int = RPC_CONSUL):
        self.transport = transport
        self.rpc_type = rpc_type
        self._conns: dict[str, _Conn] = {}
        self._dial_locks: dict[str, asyncio.Lock] = {}

    async def call(
        self, addr: str, method: str, body: dict, timeout: float = 30.0
    ) -> Any:
        conn = await self._get_conn(addr)
        conn.seq += 1
        seq = conn.seq
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        conn.waiters[seq] = fut
        try:
            await conn.stream.send(
                _pack({"seq": seq, "method": method, "body": body})
            )
            resp = await asyncio.wait_for(fut, timeout)
        except ConnectionError:
            self._drop_conn(addr, conn)
            raise
        except asyncio.TimeoutError:
            # The connection itself may be fine (e.g. a long-poll the
            # caller under-budgeted); abandoning just this call keeps the
            # other in-flight requests on the shared stream alive.
            raise
        finally:
            conn.waiters.pop(seq, None)
        if resp.get("error"):
            raise RPCError(resp["error"])
        return resp.get("body")

    async def shutdown(self) -> None:
        for addr in list(self._conns):
            self._drop_conn(addr, self._conns[addr])

    async def _get_conn(self, addr: str) -> _Conn:
        conn = self._conns.get(addr)
        if conn and not conn.dead:
            return conn
        lock = self._dial_locks.setdefault(addr, asyncio.Lock())
        async with lock:
            conn = self._conns.get(addr)
            if conn and not conn.dead:
                return conn
            stream = await self.transport.dial(addr, timeout=10.0)
            await stream.send(bytes([self.rpc_type]))
            conn = _Conn(stream)
            conn.reader = asyncio.create_task(self._read_loop(addr, conn))
            self._conns[addr] = conn
            return conn

    async def stream(
        self, addr: str, method: str, body: dict
    ) -> AsyncIterator[Any]:
        """Server-streaming call: yields each frame's body until the
        server closes the stream (the client half of Subscribe).
        Abandoning the iterator sends a cancel frame."""
        conn = await self._get_conn(addr)
        conn.seq += 1
        seq = conn.seq
        q: asyncio.Queue = asyncio.Queue()
        conn.stream_waiters[seq] = q
        finished = False
        try:
            await conn.stream.send(
                _pack({"seq": seq, "method": method, "body": body})
            )
            consumed = 0
            while True:
                item = await q.get()
                if isinstance(item, Exception):
                    finished = True
                    raise item
                if item.get("error"):
                    finished = True
                    raise RPCError(item["error"])
                if not item.get("more", False):
                    finished = True
                    return
                yield item.get("body")
                # Window update AFTER the application consumed the item
                # (yamux stream.go sendWindowUpdate): batched at half
                # the window so updates amortize.
                consumed += 1
                if consumed >= STREAM_WINDOW // 2 and not conn.dead:
                    try:
                        await conn.stream.send(
                            _pack({"seq": seq, "credit": consumed}))
                        consumed = 0
                    except Exception:  # noqa: BLE001 - conn tearing down
                        pass
        finally:
            conn.stream_waiters.pop(seq, None)
            if not finished and not conn.dead:
                # Iterator abandoned mid-stream: tell the server.
                try:
                    await conn.stream.send(_pack({"seq": seq, "cancel": True}))
                except Exception:  # noqa: BLE001 - conn already torn down
                    pass

    async def _read_loop(self, addr: str, conn: _Conn) -> None:
        try:
            while True:
                resp = _unpack(await conn.stream.recv())
                seq = resp.get("seq")
                sq = conn.stream_waiters.get(seq)
                if sq is not None:
                    sq.put_nowait(resp)
                    continue
                fut = conn.waiters.get(seq)
                if fut and not fut.done():
                    fut.set_result(resp)
        except (ConnectionError, asyncio.CancelledError, Exception) as e:
            conn.fail_all(e if isinstance(e, ConnectionError) else ConnectionError(str(e)))
            if self._conns.get(addr) is conn:
                del self._conns[addr]

    def _drop_conn(self, addr: str, conn: _Conn) -> None:
        conn.fail_all(ConnectionError(f"connection to {addr} dropped"))
        if conn.reader:
            conn.reader.cancel()
        if self._conns.get(addr) is conn:
            del self._conns[addr]


class RaftRPCAdapter:
    """Raft's transport riding the shared RPC port (server.go raftLayer:
    raft traffic is just stream type byte 1 on the same listener)."""

    def __init__(self, client: RPCClient, addr_of: Callable[[str], Optional[str]]):
        self._client = client
        self._addr_of = addr_of  # node id -> rpc addr (from serf tags)
        self._handler: Optional[Callable] = None

    def bind(self, node_id: str, handler: Callable) -> None:
        # Exactly one raft node lives in a process (server.go); a second
        # bind indicates a wiring bug, not a routing feature.
        if self._handler is not None:
            raise RuntimeError("raft handler already bound on this adapter")
        self._handler = handler

    async def handle(self, method: str, body: dict) -> dict:
        if self._handler is None:
            raise RPCError("no raft node bound")
        return await self._handler(method, body)

    async def call(self, target: str, method: str, body: dict) -> dict:
        addr = self._addr_of(target)
        if addr is None:
            raise ConnectionError(f"no known address for raft peer {target}")
        return await self._client.call(addr, method, body, timeout=10.0)
