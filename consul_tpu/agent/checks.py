"""Health check executors.

Equivalent of ``agent/checks/check.go``: each runner drives one check
definition and reports status transitions into the agent's LocalState
(which anti-entropy then pushes to the catalog).

  CheckTTL      check.go:231 — app heartbeats via the agent API; missing
                the TTL flips the check critical
  CheckMonitor  check.go:63 — run a command periodically; exit 0 =
                passing, 1 = warning, else critical
  CheckTCP      check.go:512 — connect() success = passing
  CheckHTTP     check.go:333 — GET; 2xx passing, 429 warning, else
                critical (body captured as output)
  CheckAlias    alias.go:23 — mirrors the health of another locally
                registered service: any critical -> critical, any
                warning -> warning, all passing -> passing, service
                missing -> critical

Timeouts, first-run randomization (to avoid thundering herds after an
agent restart) and output truncation follow the reference's behavior.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import random
import time
from typing import Callable, Optional

from consul_tpu.store.state import (
    HEALTH_CRITICAL,
    HEALTH_PASSING,
    HEALTH_WARNING,
)

log = logging.getLogger("consul_tpu.checks")

OUTPUT_MAX = 4096  # check.go BufSize truncation analogue

# notify(check_id, status, output)
Notify = Callable[[str, str, str], None]


class CheckRunner:
    check_id: str

    def start(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def stop(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError


@dataclasses.dataclass
class CheckTTL(CheckRunner):
    """TTL check: flips critical unless touched within ttl
    (check.go:231 + agent TTL endpoints)."""

    check_id: str
    ttl_s: float
    notify: Notify
    _task: Optional[asyncio.Task] = None
    _deadline: float = 0.0

    def start(self) -> None:
        self._deadline = time.monotonic() + self.ttl_s
        self._task = asyncio.create_task(self._run())

    def stop(self) -> None:
        if self._task:
            self._task.cancel()

    def set_status(self, status: str, output: str = "") -> None:
        """App heartbeat (pass/warn/fail endpoints): resets the timer."""
        self._deadline = time.monotonic() + self.ttl_s
        self.notify(self.check_id, status, output[:OUTPUT_MAX])

    async def _run(self) -> None:
        while True:
            now = time.monotonic()
            if now >= self._deadline:
                self.notify(
                    self.check_id,
                    HEALTH_CRITICAL,
                    f"TTL expired ({self.ttl_s}s without update)",
                )
                self._deadline = now + self.ttl_s  # re-arm; stays critical
            await asyncio.sleep(
                max(0.01, min(self._deadline - now, self.ttl_s / 2))
            )


class _PeriodicCheck(CheckRunner):
    """Common run-every-interval machinery with first-run stagger."""

    def __init__(self, check_id: str, interval_s: float, timeout_s: float,
                 notify: Notify):
        self.check_id = check_id
        self.interval_s = interval_s
        self.timeout_s = timeout_s or interval_s
        self.notify = notify
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.create_task(self._run())

    def stop(self) -> None:
        if self._task:
            self._task.cancel()

    async def _run(self) -> None:
        # Initial stagger within one interval (check.go:94-102).
        await asyncio.sleep(random.random() * min(self.interval_s, 1.0))
        while True:
            try:
                status, output = await asyncio.wait_for(
                    self._probe(), self.timeout_s
                )
            except asyncio.TimeoutError:
                status, output = HEALTH_CRITICAL, "check timed out"
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — a probe error is a result
                status, output = HEALTH_CRITICAL, str(e)
            self.notify(self.check_id, status, output[:OUTPUT_MAX])
            await asyncio.sleep(self.interval_s)

    async def _probe(self) -> tuple[str, str]:  # pragma: no cover - iface
        raise NotImplementedError


class CheckMonitor(_PeriodicCheck):
    """Script check: exit 0 passing / 1 warning / other critical
    (check.go:63 CheckMonitor)."""

    def __init__(self, check_id: str, command: str, interval_s: float,
                 notify: Notify, timeout_s: float = 30.0):
        super().__init__(check_id, interval_s, timeout_s, notify)
        self.command = command

    async def _probe(self) -> tuple[str, str]:
        proc = await asyncio.create_subprocess_shell(
            self.command,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
        )
        out, _ = await proc.communicate()
        output = out.decode(errors="replace")
        if proc.returncode == 0:
            return HEALTH_PASSING, output
        if proc.returncode == 1:
            return HEALTH_WARNING, output
        return HEALTH_CRITICAL, output


class CheckTCP(_PeriodicCheck):
    """TCP connect check (check.go:512)."""

    def __init__(self, check_id: str, addr: str, interval_s: float,
                 notify: Notify, timeout_s: float = 10.0):
        super().__init__(check_id, interval_s, timeout_s, notify)
        host, _, port = addr.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)

    async def _probe(self) -> tuple[str, str]:
        reader, writer = await asyncio.open_connection(self.host, self.port)
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:  # noqa: BLE001
            pass
        return HEALTH_PASSING, f"TCP connect {self.host}:{self.port}: Success"


class CheckHTTP(_PeriodicCheck):
    """HTTP GET check (check.go:333): 2xx passing, 429 warning, other
    critical.  Minimal HTTP/1.1 client over asyncio sockets (no external
    client library in the image)."""

    def __init__(self, check_id: str, url: str, interval_s: float,
                 notify: Notify, timeout_s: float = 10.0):
        super().__init__(check_id, interval_s, timeout_s, notify)
        self.url = url
        # Parse http://host:port/path
        rest = url.split("://", 1)[-1]
        hostport, slash, path = rest.partition("/")
        host, _, port = hostport.partition(":")
        self.host = host
        self.port = int(port or 80)
        self.path = slash + path or "/"

    async def _probe(self) -> tuple[str, str]:
        reader, writer = await asyncio.open_connection(self.host, self.port)
        try:
            writer.write(
                f"GET {self.path} HTTP/1.1\r\nHost: {self.host}\r\n"
                f"Connection: close\r\nUser-Agent: consul-tpu-check\r\n\r\n"
                .encode()
            )
            await writer.drain()
            raw = await reader.read(OUTPUT_MAX)
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass
        status_line = raw.split(b"\r\n", 1)[0].decode(errors="replace")
        parts = status_line.split()
        code = int(parts[1]) if len(parts) > 1 and parts[1].isdigit() else 0
        body = raw.split(b"\r\n\r\n", 1)[-1].decode(errors="replace")
        output = f"HTTP GET {self.url}: {code} Output: {body}"
        if 200 <= code < 300:
            return HEALTH_PASSING, output
        if code == 429:
            return HEALTH_WARNING, output
        return HEALTH_CRITICAL, output


class CheckAlias(CheckRunner):
    """alias.go:23 CheckAlias: reflect another service's health."""

    def __init__(self, check_id: str, alias_service: str,
                 lookup: Callable[[str], Optional[list[str]]],
                 notify: Notify, interval_s: float = 1.0):
        self.check_id = check_id
        self.alias_service = alias_service
        self.lookup = lookup
        self.notify = notify
        self.interval_s = interval_s
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    async def _loop(self) -> None:
        while True:
            statuses = self.lookup(self.alias_service)
            if statuses is None:
                self.notify(self.check_id, HEALTH_CRITICAL,
                            "aliased service is not registered")
            elif HEALTH_CRITICAL in statuses:
                self.notify(self.check_id, HEALTH_CRITICAL,
                            "aliased check is critical")
            elif HEALTH_WARNING in statuses:
                self.notify(self.check_id, HEALTH_WARNING,
                            "aliased check is warning")
            else:
                # No checks at all counts as passing (alias.go
                # CheckIfServiceIDExists + empty check set).
                self.notify(self.check_id, HEALTH_PASSING,
                            "all checks passing")
            await asyncio.sleep(self.interval_s)


def build_check_runner(
    defn: dict,
    notify: Notify,
    alias_lookup: Optional[Callable[[str], Optional[list[str]]]] = None,
) -> Optional[CheckRunner]:
    """Map a check definition dict to its executor (agent.go
    addCheck dispatch): ttl | script/args | tcp | http | alias."""
    cid = defn.get("check_id") or defn.get("name")
    interval = _seconds(defn.get("interval", 10.0))
    timeout = _seconds(defn.get("timeout", 0.0))
    if defn.get("alias_service"):
        if alias_lookup is None:
            return None
        return CheckAlias(cid, defn["alias_service"], alias_lookup, notify,
                          interval_s=interval or 1.0)
    if defn.get("ttl"):
        return CheckTTL(cid, _seconds(defn["ttl"]), notify)
    if defn.get("script") or defn.get("args"):
        cmd = defn.get("script") or " ".join(defn["args"])
        return CheckMonitor(cid, cmd, interval, notify,
                            timeout_s=timeout or 30.0)
    if defn.get("tcp"):
        return CheckTCP(cid, defn["tcp"], interval, notify,
                        timeout_s=timeout or 10.0)
    if defn.get("http"):
        return CheckHTTP(cid, defn["http"], interval, notify,
                         timeout_s=timeout or 10.0)
    return None  # bare catalog check with no executor


def _seconds(v) -> float:
    from consul_tpu.agent.server import _parse_ttl

    return _parse_ttl(v)
