"""Live log streaming: the ``consul monitor`` data source.

Parity model: ``logging/monitor/monitor.go`` — a sink attached to the
process's intercept logger feeds a bounded channel per subscriber;
messages beyond the buffer are DROPPED (and counted) rather than
blocking the logger; ``agent/agent_endpoint.go:1140`` (AgentMonitor)
streams the channel over chunked HTTP at a caller-chosen log level.

Here the "intercept logger" is the stdlib root logger of the
``consul_tpu`` tree: every subsystem logger (serf, raft, http, dns,
proxycfg, ...) hangs under it, so one handler observes them all.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

ROOT_LOGGER = "consul_tpu"
BUFFER_SIZE = 512  # monitor.go: "Defaults to 512"

_LEVELS = {
    "trace": logging.DEBUG,  # stdlib has no TRACE; map to DEBUG
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "err": logging.ERROR,
    "error": logging.ERROR,
}


# Live monitors per logger name + the level each logger held before the
# first monitor lowered it, so the last stop() can restore it (one
# transient API call must not durably change the agent's verbosity).
_active: dict[str, list["Monitor"]] = {}
_saved_levels: dict[str, int] = {}


class Monitor(logging.Handler):
    """monitor.go monitor: Start() yields log lines, Stop() detaches
    and reports how many lines the bounded buffer dropped."""

    def __init__(self, level_name: str = "info",
                 logger_name: str = ROOT_LOGGER,
                 buffer_size: int = BUFFER_SIZE):
        level = _LEVELS.get(level_name.lower())
        if level is None:
            raise ValueError(f"unknown log level {level_name!r}")
        super().__init__(level=level)
        self.setFormatter(logging.Formatter(
            "%(asctime)s [%(levelname)s] %(name)s: %(message)s"))
        self._logger_name = logger_name
        self._logger = logging.getLogger(logger_name)
        self._queue: asyncio.Queue[bytes] = asyncio.Queue(buffer_size)
        self.dropped = 0
        self._attached = False

    # -- logging.Handler ------------------------------------------------

    def emit(self, record: logging.LogRecord) -> None:
        try:
            line = (self.format(record) + "\n").encode()
        except Exception:  # noqa: BLE001 — a bad record must not kill logging
            return
        try:
            self._queue.put_nowait(line)
        except asyncio.QueueFull:
            # monitor.go: dropped, counted, never blocks the logger.
            self.dropped += 1

    # -- Monitor interface ----------------------------------------------

    def start(self) -> "Monitor":
        if not self._attached:
            # The monitor must see records below the tree's configured
            # level (the reference's SinkAdapter registers at its own
            # level) — lower the root logger if needed; per-record
            # filtering stays with this handler's own level.  The
            # pre-monitor level is saved once and restored when the
            # LAST live monitor detaches.
            peers = _active.setdefault(self._logger_name, [])
            if not peers:
                _saved_levels[self._logger_name] = self._logger.level
            peers.append(self)
            if self._logger.level == 0 or self._logger.level > self.level:
                self._logger.setLevel(self.level)
            self._logger.addHandler(self)
            self._attached = True
        return self

    def stop(self) -> int:
        if self._attached:
            self._logger.removeHandler(self)
            self._attached = False
            peers = _active.get(self._logger_name, [])
            if self in peers:
                peers.remove(self)
            if not peers:
                self._logger.setLevel(
                    _saved_levels.pop(self._logger_name, 0))
            else:
                # Tighten back to the least-verbose still-needed level.
                want = min(p.level for p in peers)
                saved = _saved_levels.get(self._logger_name, 0)
                self._logger.setLevel(
                    min(want, saved) if saved else want)
        return self.dropped

    async def next_line(self, timeout: Optional[float] = None) -> bytes:
        """Await the next buffered log line (the Start() channel recv)."""
        if timeout is None:
            return await self._queue.get()
        return await asyncio.wait_for(self._queue.get(), timeout)
