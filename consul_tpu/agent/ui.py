"""Built-in web UI: a single-file dashboard served at /ui.

The reference ships an Ember.js SPA (``ui/packages/consul-ui``, ~11 MB
of JS, served when ``ui = true``); this is its small-footprint
counterpart — one self-contained HTML page that drives the same
``/v1`` HTTP API from the browser (services with health, nodes, KV
browser, members, datacenters), refreshing on an interval.  No build
step, no assets, no dependencies.
"""

UI_HTML = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>consul-tpu</title>
<style>
  :root { --ok:#2eb039; --warn:#c9a206; --crit:#c73445; --ink:#1f2430;
          --mut:#6b7280; --line:#e5e7eb; --bg:#f8f9fa; }
  * { box-sizing: border-box; }
  body { font: 14px/1.5 system-ui, sans-serif; margin:0; color:var(--ink);
         background:var(--bg); }
  header { background:#1f2430; color:#fff; padding:10px 20px;
           display:flex; gap:18px; align-items:baseline; }
  header h1 { font-size:16px; margin:0; }
  header .dc { color:#9aa3b2; font-size:12px; }
  nav button { background:none; border:none; color:#c8cedb; font:inherit;
               cursor:pointer; padding:4px 8px; border-radius:4px; }
  nav button.active { background:#3b4252; color:#fff; }
  main { max-width: 1000px; margin: 20px auto; padding: 0 16px; }
  table { width:100%; border-collapse:collapse; background:#fff;
          border:1px solid var(--line); border-radius:6px; }
  th, td { text-align:left; padding:8px 12px;
           border-bottom:1px solid var(--line); }
  th { color:var(--mut); font-weight:600; font-size:12px;
       text-transform:uppercase; }
  .dot { display:inline-block; width:9px; height:9px; border-radius:50%;
         margin-right:6px; }
  .passing { background:var(--ok); } .warning { background:var(--warn); }
  .critical { background:var(--crit); } .unknown { background:#9ca3af; }
  .mut { color:var(--mut); } code { background:#eef1f4; padding:1px 5px;
         border-radius:3px; }
</style>
</head>
<body>
<header>
  <h1>consul-tpu</h1>
  <nav id="nav"></nav>
  <span class="dc" id="meta"></span>
</header>
<main><div id="view">loading…</div></main>
<script>
const TABS = ["services", "nodes", "kv", "members", "datacenters"];
let tab = location.hash.slice(1) || "services";
const $ = (id) => document.getElementById(id);
const esc = (s) => String(s).replace(/[&<>"]/g,
  (c) => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));
const get = (p) => fetch(p).then((r) => r.ok ? r.json() : []);
function worst(checks) {
  const st = (checks || []).map((c) => c.Status);
  if (st.includes("critical")) return "critical";
  if (st.includes("warning")) return "warning";
  return st.length ? "passing" : "unknown";
}
function table(head, rows) {
  return "<table><tr>" + head.map((h) => `<th>${h}</th>`).join("") +
    "</tr>" + rows.map((r) =>
      "<tr>" + r.map((c) => `<td>${c}</td>`).join("") + "</tr>"
    ).join("") + "</table>";
}
const views = {
  async services() {
    const svcs = await get("/v1/catalog/services");
    const rows = await Promise.all(Object.keys(svcs).map(async (name) => {
      const inst = await get(`/v1/health/service/${name}?stale`);
      const s = worst(inst.flatMap((i) => i.Checks || []));
      return [`<span class="dot ${s}"></span>${esc(name)}`,
              inst.length,
              (svcs[name] || []).map(esc).join(", ") || "—"];
    }));
    return table(["Service", "Instances", "Tags"], rows);
  },
  async nodes() {
    const nodes = await get("/v1/catalog/nodes?stale");
    return table(["Node", "Address"], nodes.map(
      (n) => [esc(n.Name || n.Node), `<code>${esc(n.Address)}</code>`]));
  },
  async kv() {
    const keys = await get("/v1/kv/?keys&stale") || [];
    return table(["Key"], keys.map((k) => [`<code>${esc(k)}</code>`]));
  },
  async members() {
    const ms = await get("/v1/agent/members");
    // Status is serf's MemberStatus int (none/alive/leaving/left/failed).
    const NAMES = ["none", "alive", "leaving", "left", "failed"];
    return table(["Member", "Address", "Status", "Type"], ms.map((m) => {
      const name = NAMES[m.Status] || String(m.Status);
      const s = name === "alive" ? "passing" : "critical";
      return [`<span class="dot ${s}"></span>${esc(m.Name)}`,
              `<code>${esc(m.Addr)}</code>`, esc(name),
              esc((m.Tags || {}).role || "client")];
    }));
  },
  async datacenters() {
    const dcs = await get("/v1/catalog/datacenters");
    return table(["Datacenter (RTT order)"], dcs.map((d) => [esc(d)]));
  },
};
function nav() {
  $("nav").innerHTML = TABS.map((t) =>
    `<button class="${t === tab ? "active" : ""}"
      onclick="location.hash='${t}'">${t}</button>`).join("");
}
async function render() {
  nav();
  try { $("view").innerHTML = await views[tab](); }
  catch (e) { $("view").innerHTML = `<p class="mut">${esc(e)}</p>`; }
  const self = await get("/v1/agent/self");
  $("meta").textContent =
    `${self?.Config?.NodeName || ""} · ${self?.Config?.Datacenter || ""}`;
}
window.addEventListener("hashchange", () => {
  tab = location.hash.slice(1) || "services"; render();
});
render();
setInterval(render, 5000);
</script>
</body>
</html>
"""
