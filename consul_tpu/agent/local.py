"""Agent-local catalog state + anti-entropy sync.

Equivalent of ``agent/local`` (the agent's own view of its services and
checks, with per-entry in-sync flags) and ``agent/ae`` (the sync loop
that reconciles it against the servers):

  local catalog      local/state.go — services/checks registered on
                     THIS agent, each entry carrying an InSync flag;
                     check output updates are deferred to avoid write
                     amplification (CheckUpdateInterval)
  SyncFull           local/state.go:1020 — fetch the server's view of
                     this node (Catalog.NodeServices + Health.NodeChecks),
                     deregister remote-onlys, push out-of-sync entries
  SyncChanges        local/state.go:1038 — push only dirty entries
  sync cadence       ae/ae.go:25-38 — base interval scaled by
                     log2(cluster_size/128), staggered, retried on
                     failure with backoff
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import math
import random
import time
from typing import Awaitable, Callable, Optional

from consul_tpu.store.state import HEALTH_CRITICAL, HEALTH_PASSING, SERF_CHECK_ID

log = logging.getLogger("consul_tpu.local")

# ae/ae.go constants.
SYNC_STAGGER_FRACTION = 16
RETRY_FAILED_INTERVAL_S = 15.0
SCALE_THRESHOLD = 128  # ae.go:25 aeScaleThreshold


def sync_scale_factor(cluster_size: int) -> float:
    """ae.go:31-38 scaleFactor: 1 + log2(size/threshold), floor 1."""
    if cluster_size <= SCALE_THRESHOLD:
        return 1.0
    return 1.0 + math.log2(cluster_size / SCALE_THRESHOLD)


@dataclasses.dataclass
class LocalService:
    service: dict
    in_sync: bool = False
    deleted: bool = False


@dataclasses.dataclass
class LocalCheck:
    check: dict
    in_sync: bool = False
    deleted: bool = False
    defer_until: float = 0.0  # deferred output-only update


class LocalState:
    """The agent's source-of-truth for its own registrations
    (``local.State``)."""

    def __init__(
        self,
        node_name: str,
        rpc: Callable[[str, dict], Awaitable[dict]],
        address: str = "",
        check_update_interval_s: float = 5 * 60.0,
    ):
        self.node_name = node_name
        self.address = address
        self.rpc = rpc  # client/server delegate RPC entry point
        self.check_update_interval_s = check_update_interval_s
        self.services: dict[str, LocalService] = {}
        self.checks: dict[str, LocalCheck] = {}
        self.on_change: Optional[Callable[[], None]] = None  # wakes syncer

    # -- registration (local/state.go AddService/RemoveService/...) ---------

    def _changed(self) -> None:
        if self.on_change:
            self.on_change()

    def add_service(self, service: dict) -> None:
        sid = service.get("id") or service["service"]
        service = dict(service, id=sid)
        self.services[sid] = LocalService(service=service)
        self._changed()

    def remove_service(self, service_id: str) -> bool:
        entry = self.services.get(service_id)
        if entry is None:
            return False
        entry.deleted = True
        entry.in_sync = False
        for c in self.checks.values():
            if c.check.get("service_id") == service_id:
                c.deleted = True
                c.in_sync = False
        self._changed()
        return True

    def add_check(self, check: dict) -> None:
        cid = check.get("check_id") or check["name"]
        check = dict(check, check_id=cid)
        check.setdefault("status", HEALTH_CRITICAL)
        self.checks[cid] = LocalCheck(check=check)
        self._changed()

    def remove_check(self, check_id: str) -> bool:
        entry = self.checks.get(check_id)
        if entry is None:
            return False
        entry.deleted = True
        entry.in_sync = False
        self._changed()
        return True

    def update_check(self, check_id: str, status: str, output: str = "") -> None:
        """Check executor callback (local/state.go UpdateCheck): a pure
        output change is deferred up to CheckUpdateInterval to avoid
        constant catalog writes; a status change syncs immediately."""
        entry = self.checks.get(check_id)
        if entry is None or entry.deleted:
            return
        now = time.monotonic()
        if entry.check["status"] == status:
            if entry.check.get("output") == output:
                return
            entry.check["output"] = output
            if entry.defer_until == 0.0:
                entry.defer_until = now + self.check_update_interval_s
            if now < entry.defer_until:
                return  # deferred; SyncFull will pick it up eventually
        else:
            entry.check["status"] = status
            entry.check["output"] = output
        entry.defer_until = 0.0
        entry.in_sync = False
        self._changed()

    def service_records(self) -> list[dict]:
        return [e.service for e in self.services.values() if not e.deleted]

    def check_records(self) -> list[dict]:
        return [e.check for e in self.checks.values() if not e.deleted]

    # -- sync (local/state.go SyncFull/SyncChanges) -------------------------

    async def sync_full(self) -> None:
        """Reconcile against the servers' view of this node."""
        remote_svcs: dict[str, dict] = {}
        remote_checks: dict[str, dict] = {}
        out = await self.rpc(
            "Catalog.NodeServices", {"node": self.node_name, "allow_stale": True}
        )
        for svc in out.get("services") or []:
            remote_svcs[svc["id"]] = svc
        out = await self.rpc(
            "Health.NodeChecks", {"node": self.node_name, "allow_stale": True}
        )
        for chk in out.get("checks") or []:
            remote_checks[chk["check_id"]] = chk

        # Remote-only services/checks were registered by an old
        # incarnation: deregister (except the serf health check, which
        # the leader owns).
        for sid in remote_svcs:
            if sid not in self.services or self.services[sid].deleted:
                await self._deregister(service_id=sid)
        for cid in remote_checks:
            if cid == SERF_CHECK_ID:
                continue
            if cid not in self.checks or self.checks[cid].deleted:
                await self._deregister(check_id=cid)

        # Mark local entries out-of-sync when remote disagrees.  Local
        # dicts are normalized with the catalog's own defaults first
        # (state.py _ensure_service_txn/_ensure_check_txn), otherwise a
        # missing key (None) vs server default ('') would flag every
        # entry dirty and re-register the world each interval.
        for sid, entry in self.services.items():
            remote = remote_svcs.get(sid)
            local = entry.service
            entry.in_sync = (
                not entry.deleted
                and remote is not None
                and remote.get("service") == local.get("service")
                and int(remote.get("port", 0)) == int(local.get("port", 0))
                and (remote.get("address") or "") == (local.get("address") or "")
                and list(remote.get("tags") or []) == list(local.get("tags") or [])
            )
        for cid, entry in self.checks.items():
            remote = remote_checks.get(cid)
            local = entry.check
            entry.in_sync = (
                not entry.deleted
                and remote is not None
                and remote.get("status") == local.get("status")
                and (remote.get("output") or "") == (local.get("output") or "")
            )
        await self.sync_changes()

    async def sync_changes(self) -> None:
        """Push every dirty entry (local/state.go SyncChanges)."""
        for sid, entry in list(self.services.items()):
            if entry.deleted:
                await self._deregister(service_id=sid)
                # The id may have been re-registered while the RPC was
                # in flight — only drop the entry we deregistered.
                if self.services.get(sid) is entry:
                    del self.services[sid]
            elif not entry.in_sync:
                await self._register_service(entry)
        for cid, entry in list(self.checks.items()):
            if entry.deleted:
                await self._deregister(check_id=cid)
                if self.checks.get(cid) is entry:
                    del self.checks[cid]
            elif not entry.in_sync:
                await self._register_check(entry)

    async def _register_service(self, entry: LocalService) -> None:
        svc = entry.service
        checks = [
            c.check
            for c in self.checks.values()
            if not c.deleted and c.check.get("service_id") == svc["id"]
        ]
        await self.rpc(
            "Catalog.Register",
            {
                "node": self.node_name,
                "address": self.address,
                "service": svc,
                "checks": checks,
            },
        )
        entry.in_sync = True
        for c in self.checks.values():
            if not c.deleted and c.check.get("service_id") == svc["id"]:
                c.in_sync = True

    async def _register_check(self, entry: LocalCheck) -> None:
        await self.rpc(
            "Catalog.Register",
            {
                "node": self.node_name,
                "address": self.address,
                "check": entry.check,
            },
        )
        entry.in_sync = True

    async def _deregister(
        self, service_id: str = "", check_id: str = ""
    ) -> None:
        body: dict = {"node": self.node_name}
        if service_id:
            body["service_id"] = service_id
        if check_id:
            body["check_id"] = check_id
        await self.rpc("Catalog.Deregister", body)


class StateSyncer:
    """The anti-entropy pacing loop (``ae/ae.go:44-151``): full sync on
    start, then periodically (interval scaled by cluster size), with
    edge-triggered partial syncs in between and retry-with-stagger on
    failure."""

    def __init__(
        self,
        state: LocalState,
        cluster_size: Callable[[], int],
        sync_interval_s: float = 60.0,
        retry_interval_s: float = RETRY_FAILED_INTERVAL_S,
        rng: Optional[random.Random] = None,
    ):
        self.state = state
        self.cluster_size = cluster_size
        self.sync_interval_s = sync_interval_s
        self.retry_interval_s = retry_interval_s
        self._rng = rng or random.Random()
        self._changes = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        state.on_change = self._changes.set
        self.synced_once = asyncio.Event()

    def start(self) -> None:
        self._task = asyncio.create_task(self._run())

    def stop(self) -> None:
        if self._task:
            self._task.cancel()

    def _stagger(self, interval: float) -> float:
        return interval + self._rng.random() * interval / SYNC_STAGGER_FRACTION

    async def _run(self) -> None:
        while True:
            try:
                await self.state.sync_full()
                self.synced_once.set()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — retry on any RPC failure
                log.warning("anti-entropy full sync failed: %s", e)
                await asyncio.sleep(self._stagger(self.retry_interval_s))
                continue
            # Between full syncs, service edge-triggered changes.
            interval = self._stagger(
                self.sync_interval_s * sync_scale_factor(self.cluster_size())
            )
            deadline = time.monotonic() + interval
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    await asyncio.wait_for(self._changes.wait(), remaining)
                except asyncio.TimeoutError:
                    break
                self._changes.clear()
                try:
                    await self.state.sync_changes()
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001
                    log.warning("anti-entropy partial sync failed: %s", e)
                    break  # fall through to a full sync + retry pacing
