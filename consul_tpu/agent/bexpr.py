"""Boolean-expression result filtering: the ``?filter=`` query param.

Equivalent of the vendored ``go-bexpr`` used by ``agent/http.go``
(parseFilter → bexpr.CreateFilter): list endpoints accept a filter
expression evaluated against each (camelized) result row, e.g.

    ServiceName == "web" and Checks.Status != "critical"
    "primary" in ServiceTags
    Node.Meta.env is not empty
    ServiceName matches "web-.*"

Grammar (the go-bexpr surface, minus struct-tag pointers):

    expr        := or
    or          := and ("or" and)*
    and         := unary ("and" unary)*
    unary       := "not" unary | "(" expr ")" | comparison
    comparison  := selector binop value
                 | value ("in" | "not in") selector
                 | selector ("contains" | "not contains") value
                 | selector "is" ["not"] "empty"
                 | selector ["not"] "matches" value
    binop       := "==" | "!="
    selector    := Ident ("." Ident)*
    value       := "string" | `string` | number | true | false

Selectors traverse nested dicts; a selector that crosses a LIST fans
out over the elements and the comparison succeeds if ANY element
matches (go-bexpr collection semantics for membership-style use).
"""

from __future__ import annotations

import re
from typing import Any


class FilterError(ValueError):
    """Bad filter expression (400 at the HTTP layer)."""


_TOKEN_RE = re.compile(
    r"""
    \s*(?:
        (?P<lparen>\() | (?P<rparen>\))
      | (?P<eq>==) | (?P<ne>!=)
      | (?P<string>"(?:[^"\\]|\\.)*"|`[^`]*`)
      | (?P<number>-?\d+(?:\.\d+)?)
      | (?P<ident>[A-Za-z_][A-Za-z0-9_-]*(?:\.[A-Za-z0-9_-]+)*)
    )
    """,
    re.VERBOSE,
)

_KEYWORDS = {"and", "or", "not", "in", "contains", "is", "empty",
             "matches", "true", "false"}


def _tokenize(src: str) -> list[tuple[str, str]]:
    out, pos = [], 0
    while pos < len(src):
        if src[pos:].strip() == "":
            break
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise FilterError(f"bad filter syntax at {src[pos:pos+20]!r}")
        pos = m.end()
        kind = m.lastgroup
        text = m.group(kind)
        if kind == "ident" and text.lower() in _KEYWORDS and "." not in text:
            out.append((text.lower(), text))
        else:
            out.append((kind, text))
    out.append(("eof", ""))
    return out


def _resolve(row: Any, path: list[str]) -> list[Any]:
    """Selector traversal; lists fan out (any-match semantics)."""
    values = [row]
    for part in path:
        nxt: list[Any] = []
        for v in values:
            if isinstance(v, list):
                v_items = v
            else:
                v_items = [v]
            for item in v_items:
                if isinstance(item, dict) and part in item:
                    nxt.append(item[part])
        values = nxt
        if not values:
            return []
    # Final fan-out: a trailing list selector exposes BOTH the list
    # itself (so `in`/`is empty` see it) and its elements (so ==/matches
    # compare against each element, go-bexpr any-match semantics).
    flat: list[Any] = []
    for v in values:
        flat.append(v)
        if isinstance(v, list):
            flat.extend(v)
    return flat


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.tokens = tokens
        self.pos = 0

    def peek(self) -> tuple[str, str]:
        return self.tokens[self.pos]

    def next(self) -> tuple[str, str]:
        tok = self.tokens[self.pos]
        self.pos += 1
        return tok

    def expect(self, kind: str) -> str:
        k, text = self.next()
        if k != kind:
            raise FilterError(f"expected {kind}, got {text!r}")
        return text

    # -- grammar -------------------------------------------------------

    def parse(self):
        node = self.parse_or()
        if self.peek()[0] != "eof":
            raise FilterError(f"unexpected {self.peek()[1]!r}")
        return node

    def parse_or(self):
        left = self.parse_and()
        while self.peek()[0] == "or":
            self.next()
            right = self.parse_and()
            left = ("or", left, right)
        return left

    def parse_and(self):
        left = self.parse_unary()
        while self.peek()[0] == "and":
            self.next()
            right = self.parse_unary()
            left = ("and", left, right)
        return left

    def parse_unary(self):
        kind, _ = self.peek()
        if kind == "not":
            self.next()
            return ("not", self.parse_unary())
        if kind == "lparen":
            self.next()
            node = self.parse_or()
            self.expect("rparen")
            return node
        return self.parse_comparison()

    def _value(self):
        kind, text = self.next()
        if kind == "string":
            return text[1:-1] if text[0] == "`" else _unescape(text[1:-1])
        if kind == "number":
            return float(text) if "." in text else int(text)
        if kind in ("true", "false"):
            return kind == "true"
        raise FilterError(f"expected a value, got {text!r}")

    def parse_comparison(self):
        kind, text = self.peek()
        if kind in ("string", "number", "true", "false"):
            # <Value> in <Selector> / <Value> not in <Selector>
            value = self._value()
            negate = False
            if self.peek()[0] == "not":
                self.next()
                negate = True
            k, t = self.next()
            if k != "in":
                raise FilterError(f"expected 'in', got {t!r}")
            sel = self.expect("ident").split(".")
            node = ("in", value, sel)
            return ("not", node) if negate else node
        sel = self.expect("ident").split(".")
        k, t = self.next()
        if k == "eq":
            return ("==", sel, self._value())
        if k == "ne":
            return ("!=", sel, self._value())
        if k == "contains":
            return ("in", self._value(), sel)
        if k == "matches":
            return ("matches", sel, self._value())
        if k == "not":
            k2, t2 = self.next()
            if k2 == "contains":
                return ("not", ("in", self._value(), sel))
            if k2 == "matches":
                return ("not", ("matches", sel, self._value()))
            raise FilterError(f"unexpected {t2!r} after 'not'")
        if k == "is":
            negate = False
            if self.peek()[0] == "not":
                self.next()
                negate = True
            self.expect("empty")
            node = ("empty", sel)
            return ("not", node) if negate else node
        if k == "in":
            # <Selector> in <Value-selector>? go-bexpr only allows
            # value-in-selector; mirror its error.
            raise FilterError("left side of 'in' must be a value")
        raise FilterError(f"expected an operator, got {t!r}")


def _unescape(s: str) -> str:
    return s.replace('\\"', '"').replace("\\\\", "\\")


def _loose_eq(a: Any, b: Any) -> bool:
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b if isinstance(a, bool) and isinstance(b, bool) else False
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return float(a) == float(b)
    return a == b


def _eval(node, row: Any) -> bool:
    op = node[0]
    if op == "and":
        return _eval(node[1], row) and _eval(node[2], row)
    if op == "or":
        return _eval(node[1], row) or _eval(node[2], row)
    if op == "not":
        return not _eval(node[1], row)
    if op == "==":
        values = _resolve(row, node[1])
        return any(_loose_eq(v, node[2]) for v in values)
    if op == "!=":
        values = _resolve(row, node[1])
        # go-bexpr: != over a collection means NO element equals.
        return not any(_loose_eq(v, node[2]) for v in values)
    if op == "in":
        values = _resolve(row, node[2])
        for v in values:
            if isinstance(v, list) and any(
                _loose_eq(item, node[1]) for item in v
            ):
                return True
            if isinstance(v, dict) and node[1] in v:
                return True
            if isinstance(v, str) and isinstance(node[1], str) \
                    and node[1] in v:
                return True
            if _loose_eq(v, node[1]):
                return True
        return False
    if op == "empty":
        values = _resolve(row, node[1])
        if not values:
            return True
        return all(
            v is None or v == "" or v == [] or v == {} for v in values
        )
    if op == "matches":
        try:
            rx = re.compile(str(node[2]))
        except re.error as e:
            raise FilterError(f"bad regex {node[2]!r}: {e}") from e
        return any(
            isinstance(v, str) and rx.search(v)
            for v in _resolve(row, node[1])
        )
    raise FilterError(f"unknown op {op!r}")


class Filter:
    """bexpr.Filter: compile once, apply to many rows."""

    def __init__(self, expression: str):
        self._ast = _Parser(_tokenize(expression)).parse()

    def match(self, row: Any) -> bool:
        return _eval(self._ast, row)

    def apply(self, rows: list) -> list:
        return [r for r in rows if self.match(r)]


def create_filter(expression: str) -> Filter:
    return Filter(expression)
