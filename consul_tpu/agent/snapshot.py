"""User-facing snapshot save/restore: atomic state archives.

Equivalent of ``snapshot/snapshot.go`` + ``archive.go`` (SURVEY.md
§2.3): a snapshot is a gzipped tar containing

    meta.json    raft index/term + the saving node (archive.go writeMeta)
    state.bin    msgpack of the FSM snapshot (the whole state store)
    SHA256SUMS   manifest over the other two files, verified byte-for-
                 byte on restore (archive.go checksums — a corrupted or
                 tampered archive is rejected before any state changes)

Restore is leader-driven and replicated: the unpacked state rides ONE
raft entry (the Restore message), so every replica installs the same
snapshot at the same log position — the in-process counterpart of the
reference's raft.Restore + InstallSnapshot propagation
(consul/snapshot_endpoint.go).
"""

from __future__ import annotations

import gzip
import hashlib
import io
import json
import tarfile
import time
from typing import Any, Optional

import msgpack


class SnapshotError(Exception):
    """Bad archive: corrupt, tampered, or incomplete."""


def _tar_add(tar: tarfile.TarFile, name: str, data: bytes) -> None:
    info = tarfile.TarInfo(name)
    info.size = len(data)
    info.mtime = 0  # deterministic archives
    tar.addfile(info, io.BytesIO(data))


def write_archive(state: Any, index: int, term: int, node: str) -> bytes:
    """Pack an FSM snapshot into the tar.gz + SHA256SUMS format."""
    state_bin = msgpack.packb(state, use_bin_type=True)
    meta = json.dumps(
        {"index": index, "term": term, "node": node, "version": 1}
    ).encode()
    sums = "".join(
        f"{hashlib.sha256(data).hexdigest()}  {name}\n"
        for name, data in (("meta.json", meta), ("state.bin", state_bin))
    ).encode()
    buf = io.BytesIO()
    # mtime=0: archives for identical state are byte-identical.
    with gzip.GzipFile(fileobj=buf, mode="wb", mtime=0) as gz:
        with tarfile.open(fileobj=gz, mode="w") as tar:
            _tar_add(tar, "meta.json", meta)
            _tar_add(tar, "state.bin", state_bin)
            _tar_add(tar, "SHA256SUMS", sums)
    return buf.getvalue()


def read_archive(blob: bytes) -> tuple[Any, dict]:
    """Unpack + verify; returns (state, meta).  Raises SnapshotError on
    any integrity failure (archive.go read + checksum verify)."""
    try:
        with gzip.GzipFile(fileobj=io.BytesIO(blob)) as gz:
            with tarfile.open(fileobj=gz, mode="r") as tar:
                files = {}
                for member in tar.getmembers():
                    fh = tar.extractfile(member)
                    if fh is not None:
                        files[member.name] = fh.read()
    except (OSError, tarfile.TarError, EOFError) as e:
        raise SnapshotError(f"unreadable archive: {e}") from e
    for required in ("meta.json", "state.bin", "SHA256SUMS"):
        if required not in files:
            raise SnapshotError(f"archive missing {required}")
    expected: dict[str, str] = {}
    for line in files["SHA256SUMS"].decode().splitlines():
        digest, _, name = line.partition("  ")
        if name:
            expected[name] = digest
    for name in ("meta.json", "state.bin"):
        actual = hashlib.sha256(files[name]).hexdigest()
        if expected.get(name) != actual:
            raise SnapshotError(f"checksum mismatch for {name}")
    meta = json.loads(files["meta.json"])
    state = msgpack.unpackb(
        files["state.bin"], raw=False, strict_map_key=False
    )
    return state, meta
