"""Config system: files + flags → a frozen, validated RuntimeConfig.

Equivalent of ``agent/config`` (SURVEY.md §2.3): any number of config
files (JSON, or the HCL subset below) plus CLI flags are merged in
order — later sources win scalars, list-valued fields append — then
validated into an immutable :class:`RuntimeConfig`
(``config/builder.go``, ``runtime.go``, ``default.go``).  Gossip tuning
is exposed as ``gossip_lan`` / ``gossip_wan`` blocks layered over the
built-in LAN/WAN profiles (``config/default.go`` GossipLANConfig).

Partial reload (``agent.go reloadConfigInternal``): service/check
definitions and a small set of runtime knobs can change on SIGHUP;
identity and cluster topology fields cannot — :func:`reloadable_diff`
separates the two.

HCL subset grammar (enough for the reference's common config shapes):

    key = "value"            # string / number / true / false
    key = [ "a", "b" ]       # lists
    block_name {             # nested object
        inner = 1
    }
    # comments and // comments
"""

from __future__ import annotations

import dataclasses
import json
import re
from pathlib import Path
from typing import Any, Optional

from consul_tpu.protocol.profiles import LAN, WAN, GossipProfile

# Fields whose list values APPEND across sources (builder.go merge).
_APPEND_FIELDS = {"services", "checks", "retry_join", "retry_join_wan"}

# Fields that may change on reload (agent.go reloadConfigInternal:
# services, checks, and a few runtime knobs; everything else requires a
# restart).
RELOADABLE = {
    "services", "checks", "dns_only_passing", "dns_node_ttl_s",
    "dns_recursors", "log_level",
}

_GOSSIP_TUNABLES = (
    "gossip_interval_ms", "probe_interval_ms", "probe_timeout_ms",
    "suspicion_mult", "retransmit_mult", "gossip_nodes",
    "push_pull_interval_ms", "indirect_checks",
)


@dataclasses.dataclass(frozen=True)
class RuntimeConfig:
    """The validated, immutable runtime configuration
    (``config/runtime.go`` RuntimeConfig)."""

    node_name: str = "node"
    datacenter: str = "dc1"
    server: bool = False
    bootstrap_expect: int = 1
    # Persistence root: the serf gossip snapshot lives at
    # <data_dir>/serf/local.snapshot (config "data_dir").
    data_dir: str = ""
    rejoin_after_leave: bool = False
    # WAN replication (secondary DCs pull from the primary).
    primary_datacenter: str = ""
    acl_replication_token: str = ""
    bind_addr: str = "127.0.0.1"
    ports_http: int = 8500
    ports_dns: int = 8600
    ports_serf_lan: int = 8301
    ports_serf_wan: int = 8302
    ports_server: int = 8300
    retry_join: tuple = ()
    retry_join_wan: tuple = ()
    log_level: str = "info"
    # Gossip encryption key, base64 (config "encrypt"; consul keygen).
    encrypt: str = ""
    # Gossip tuning blocks (resolved to profiles via gossip_profile()).
    gossip_lan: tuple = ()   # ((key, value), ...) hashable overrides
    gossip_wan: tuple = ()
    # ACL block.
    acl_enabled: bool = False
    acl_default_policy: str = "allow"
    acl_master_token: str = ""
    acl_agent_token: str = ""
    # Agent behavior.
    enable_script_checks: bool = False
    dns_only_passing: bool = True
    dns_node_ttl_s: float = 0.0
    # Upstream resolvers for non-.consul names (config "recursors").
    dns_recursors: tuple = ()
    # auto_config (agent/auto-config/config.go): client bootstrap via a
    # JWT intro token; servers hold the authorizer spec.
    auto_config_enabled: bool = False
    auto_config_intro_token: str = ""
    auto_config_server_addresses: tuple = ()
    auto_config_authorizer: object = None
    reconcile_interval_s: float = 60.0
    sync_interval_s: float = 60.0
    gossip_interval_scale: float = 1.0
    # Service/check definitions from config files (agent/structs
    # ServiceDefinition / CheckDefinition as plain dicts).
    services: tuple = ()
    checks: tuple = ()

    def gossip_profile(self, wan: bool = False) -> GossipProfile:
        """LAN/WAN base profile + the tuning block's overrides
        (config/default.go GossipLANConfig/GossipWANConfig)."""
        base = WAN if wan else LAN
        overrides = dict(self.gossip_wan if wan else self.gossip_lan)
        if not overrides:
            return base
        return dataclasses.replace(base, **overrides)


class ConfigError(ValueError):
    """Invalid or unknown configuration (builder.go Validate)."""


# ---------------------------------------------------------------------------
# HCL subset parser
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    \s*(?:
        (?P<comment>\#[^\n]*|//[^\n]*)
      | (?P<lbrace>\{) | (?P<rbrace>\})
      | (?P<lbrack>\[) | (?P<rbrack>\])
      | (?P<eq>=) | (?P<comma>,)
      | (?P<string>"(?:[^"\\]|\\.)*")
      | (?P<number>-?\d+(?:\.\d+)?)
      | (?P<bool>true|false)
      | (?P<ident>[A-Za-z_][A-Za-z0-9_.-]*)
    )
    """,
    re.VERBOSE,
)


def _tokenize_hcl(src: str) -> list[tuple[str, str]]:
    out, pos = [], 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            if src[pos:].strip() == "":
                break
            raise ConfigError(f"bad HCL at offset {pos}: {src[pos:pos+20]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind and kind != "comment":
            out.append((kind, m.group(kind)))
    return out


def parse_hcl(src: str) -> dict:
    """Parse the HCL subset into a dict (hcl/hcl parser's JSON view)."""
    tokens = _tokenize_hcl(src)
    pos = 0

    def parse_value():
        nonlocal pos
        kind, text = tokens[pos]
        if kind == "string":
            pos += 1
            return json.loads(text)
        if kind == "number":
            pos += 1
            return float(text) if "." in text else int(text)
        if kind == "bool":
            pos += 1
            return text == "true"
        if kind == "lbrack":
            pos += 1
            items = []
            while tokens[pos][0] != "rbrack":
                items.append(parse_value())
                if tokens[pos][0] == "comma":
                    pos += 1
            pos += 1
            return items
        if kind == "lbrace":
            return parse_object()
        raise ConfigError(f"unexpected HCL token {text!r}")

    def parse_object():
        nonlocal pos
        assert tokens[pos][0] == "lbrace"
        pos += 1
        obj: dict = {}
        while tokens[pos][0] != "rbrace":
            obj.update(parse_entry())
        pos += 1
        return obj

    def parse_entry():
        nonlocal pos
        kind, text = tokens[pos]
        if kind not in ("ident", "string"):
            raise ConfigError(f"expected key, got {text!r}")
        key = json.loads(text) if kind == "string" else text
        pos += 1
        kind, _ = tokens[pos]
        if kind == "eq":
            pos += 1
            return {key: parse_value()}
        if kind == "lbrace":
            # `services { ... }` block syntax: repeated blocks of the
            # same name accumulate into a list (hcl list semantics).
            return {key: parse_object()}
        raise ConfigError(f"expected '=' or block after {key!r}")

    out: dict = {}
    accumulate = _APPEND_FIELDS | {"service", "check"}
    while pos < len(tokens):
        for key, value in parse_entry().items():
            if key in out and key in accumulate:
                prev = out[key]
                prev = prev if isinstance(prev, list) else [prev]
                nxt = value if isinstance(value, list) else [value]
                out[key] = prev + nxt
            else:
                out[key] = value
    return out


# ---------------------------------------------------------------------------
# builder
# ---------------------------------------------------------------------------

_FIELDS = {f.name: f for f in dataclasses.fields(RuntimeConfig)}

# Nested block spellings accepted from files (builder.go mapping of the
# reference's config JSON shapes onto flat runtime fields).
_BLOCKS = {
    "acl": {
        "enabled": "acl_enabled",
        "default_policy": "acl_default_policy",
        "tokens.master": "acl_master_token",
        "tokens.agent": "acl_agent_token",
    },
    "dns_config": {
        "only_passing": "dns_only_passing",
        "node_ttl_s": "dns_node_ttl_s",
        "recursors": "dns_recursors",
    },
    "auto_config": {
        "enabled": "auto_config_enabled",
        "intro_token": "auto_config_intro_token",
        "server_addresses": "auto_config_server_addresses",
        "authorization": "auto_config_authorizer",
    },
    "ports": {
        "http": "ports_http",
        "dns": "ports_dns",
        "serf_lan": "ports_serf_lan",
        "serf_wan": "ports_serf_wan",
        "server": "ports_server",
    },
}


def _flatten(raw: dict, source: str) -> dict:
    """One file/flag dict → flat {runtime_field: value}."""
    flat: dict = {}
    for key, value in raw.items():
        if key in ("gossip_lan", "gossip_wan"):
            if not isinstance(value, dict):
                raise ConfigError(f"{source}: {key} must be a block")
            unknown = set(value) - set(_GOSSIP_TUNABLES)
            if unknown:
                raise ConfigError(
                    f"{source}: unknown {key} tunables {sorted(unknown)}"
                )
            flat[key] = tuple(sorted(value.items()))
            continue
        if key in _BLOCKS:
            if not isinstance(value, dict):
                raise ConfigError(f"{source}: {key} must be a block")
            mapping = _BLOCKS[key]
            for sub, subval in value.items():
                if sub in mapping:
                    # Direct mapping wins — a dict value here is the
                    # field's value wholesale (auto_config.authorization).
                    flat[mapping[sub]] = subval
                elif isinstance(subval, dict):
                    for s2, v2 in subval.items():
                        field = mapping.get(f"{sub}.{s2}")
                        if field is None:
                            raise ConfigError(
                                f"{source}: unknown key {key}.{sub}.{s2}"
                            )
                        flat[field] = v2
                else:
                    raise ConfigError(f"{source}: unknown key {key}.{sub}")
            continue
        if key in ("service", "check"):
            field = "services" if key == "service" else "checks"
            items = value if isinstance(value, list) else [value]
            flat[field] = list(flat.get(field, [])) + items
            continue
        if key not in _FIELDS:
            raise ConfigError(f"{source}: unknown configuration key {key!r}")
        flat[key] = value
    return flat


class Builder:
    """config/builder.go Builder: sources in, RuntimeConfig out."""

    def __init__(self) -> None:
        self._sources: list[tuple[str, dict]] = []

    def add_file(self, path: str | Path) -> "Builder":
        path = Path(path)
        text = path.read_text()
        if path.suffix == ".json":
            raw = json.loads(text or "{}")
        elif path.suffix == ".hcl":
            raw = parse_hcl(text)
        else:
            # Sniff: JSON object vs HCL (builder.go tries both).
            try:
                raw = json.loads(text)
            except json.JSONDecodeError:
                raw = parse_hcl(text)
        self._sources.append((str(path), raw))
        return self

    def add_dir(self, path: str | Path) -> "Builder":
        """Config dir: *.json + *.hcl in lexical order (builder.go)."""
        for p in sorted(Path(path).iterdir()):
            if p.suffix in (".json", ".hcl"):
                self.add_file(p)
        return self

    def add_flags(self, flags: dict) -> "Builder":
        """CLI flags merge LAST (highest precedence, builder.go)."""
        self._sources.append(("flags", {
            k: v for k, v in flags.items() if v is not None
        }))
        return self

    def build(self) -> RuntimeConfig:
        merged: dict = {}
        for source, raw in self._sources:
            flat = _flatten(raw, source)
            for key, value in flat.items():
                if key in _APPEND_FIELDS:
                    merged[key] = tuple(merged.get(key, ())) + tuple(
                        value if isinstance(value, (list, tuple)) else [value]
                    )
                else:
                    merged[key] = value
        # Freeze nested dicts (service/check definitions) for hashing.
        for key in ("services", "checks"):
            if key in merged:
                merged[key] = tuple(
                    _freeze(v) for v in merged[key]
                )
        for key in ("dns_recursors", "auto_config_server_addresses"):
            if key in merged:
                v = merged[key]
                merged[key] = tuple(
                    v if isinstance(v, (list, tuple)) else [v]
                )
        rc = RuntimeConfig(**merged)
        _validate(rc)
        return rc


def _freeze(value):
    if isinstance(value, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in value.items()))
    if isinstance(value, list):
        return tuple(_freeze(v) for v in value)
    return value


def thaw(value):
    """Inverse of _freeze for consumers that want plain dicts."""
    if isinstance(value, tuple) and all(
        isinstance(i, tuple) and len(i) == 2 and isinstance(i[0], str)
        for i in value
    ) and value:
        return {k: thaw(v) for k, v in value}
    if isinstance(value, tuple):
        return [thaw(v) for v in value]
    return value


def _validate(rc: RuntimeConfig) -> None:
    """builder.go Validate: the checks that catch real foot-guns."""
    if not rc.node_name:
        raise ConfigError("node_name must not be empty")
    if rc.bootstrap_expect < 1:
        raise ConfigError("bootstrap_expect must be >= 1")
    if rc.bootstrap_expect > 1 and not rc.server:
        raise ConfigError("bootstrap_expect requires server mode")
    if rc.acl_default_policy not in ("allow", "deny"):
        raise ConfigError(
            f"acl default_policy must be allow|deny, got "
            f"{rc.acl_default_policy!r}"
        )
    for blk in (rc.gossip_lan, rc.gossip_wan):
        for key, value in blk:
            if not isinstance(value, (int, float)) or value <= 0:
                raise ConfigError(f"gossip tunable {key} must be positive")
    for svc in rc.services:
        if not dict(svc).get("service") and not dict(svc).get("name"):
            raise ConfigError("service definition needs a name")
    for chk in rc.checks:
        d = dict(chk)
        if not (d.get("ttl") or d.get("http") or d.get("tcp")
                or d.get("script") or d.get("args")):
            raise ConfigError(
                "check definition needs ttl/http/tcp/script"
            )


def reloadable_diff(old: RuntimeConfig, new: RuntimeConfig) -> dict:
    """Split a config change into what reload can apply.

    Returns {field: new_value} for changed RELOADABLE fields; raises
    ConfigError listing changed non-reloadable fields (the reference
    logs and ignores them; failing loudly is kinder)."""
    changed_fixed = []
    apply: dict = {}
    for f in dataclasses.fields(RuntimeConfig):
        ov, nv = getattr(old, f.name), getattr(new, f.name)
        if ov == nv:
            continue
        if f.name in RELOADABLE:
            apply[f.name] = nv
        else:
            changed_fixed.append(f.name)
    if changed_fixed:
        raise ConfigError(
            "non-reloadable fields changed (restart required): "
            + ", ".join(sorted(changed_fixed))
        )
    return apply
