"""The HTTP API: ``/v1/...`` endpoints over a hand-rolled asyncio
HTTP/1.1 server (no web framework in the image).

Equivalent of ``agent/http.go`` + the ``agent/*_endpoint.go`` handlers
registered in ``http_register.go:1-125``.  Behaviors kept from the
reference:

  blocking queries    ?index=N&wait=10s → min_query_index/max_query_time;
                      results carry X-Consul-Index /
                      X-Consul-KnownLeader / X-Consul-LastContact
                      (http.go setMeta)
  consistency modes   ?stale / ?consistent (http.go parseConsistency)
  KV flags            ?recurse ?keys ?separator ?raw ?cas ?flags
                      ?acquire ?release (kvs_endpoint.go)
  JSON shape          CamelCase keys with ID/TTL/... acronyms upper-cased
                      (structs' JSON tags); KV Value base64-encoded
  errors              405 with Allow header, 404 unknown route,
                      400 malformed input, 500 with error text

The server binds a plain TCP port; send requests with any HTTP client.
"""

from __future__ import annotations

import asyncio
import base64
import functools
import gzip
import json
import logging
import re
import urllib.parse
from typing import Any, Callable, Optional

from consul_tpu.agent.agent import Agent
from consul_tpu.agent.bexpr import FilterError
from consul_tpu.agent.rpc import (
    ERR_ACL_NOT_FOUND,
    ERR_PERMISSION_DENIED,
    RPCError,
)
from consul_tpu.agent.server import _parse_ttl
from consul_tpu.telemetry import metrics
from consul_tpu.version import __version__

log = logging.getLogger("consul_tpu.http")

_STATUS_TEXT = {200: "OK", 307: "Temporary Redirect",
                400: "Bad Request", 403: "Forbidden",
                404: "Not Found", 405: "Method Not Allowed",
                500: "Internal Server Error"}

_ACRONYMS = {
    "Id": "ID", "Ttl": "TTL", "Dns": "DNS", "Http": "HTTP", "Tcp": "TCP",
    "Rpc": "RPC", "Wan": "WAN", "Lan": "LAN", "Cas": "CAS", "Acl": "ACL",
    "Pem": "PEM", "Uri": "URI", "Ca": "CA",
}


@functools.lru_cache(maxsize=4096)
def _camel_key(key: str) -> str:
    # Memoized: response shapes reuse a small fixed key vocabulary, and
    # key camelization dominated the hot read path before caching.
    parts = [p.capitalize() for p in key.split("_")]
    parts = [_ACRONYMS.get(p, p) for p in parts]
    return "".join(parts)


class KeyedMap(dict):
    """A dict whose keys are DATA (service names, check ids, kv keys),
    not struct fields — camelize leaves the keys alone."""


def camelize(obj: Any) -> Any:
    """snake_case dict keys → the reference's CamelCase JSON shape."""
    if isinstance(obj, KeyedMap):
        return {k: camelize(v) for k, v in obj.items()}
    if isinstance(obj, dict):
        return {_camel_key(k): camelize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [camelize(v) for v in obj]
    if isinstance(obj, bytes):
        return base64.b64encode(obj).decode()
    return obj


class HTTPRequest:
    def __init__(self, method: str, path: str, query: dict, headers: dict,
                 body: bytes):
        self.method = method
        self.path = path
        self.query = query  # first-value dict
        self.headers = headers
        self.body = body

    def flag(self, name: str) -> bool:
        """?stale style presence flag (http.go parseQuery)."""
        return name in self.query

    def json(self) -> Any:
        if not self.body:
            return {}
        return json.loads(self.body)

    def token(self) -> str:
        """http.go parseToken: ?token= beats the X-Consul-Token header."""
        return self.query.get("token") or self.headers.get(
            "x-consul-token", ""
        )

    def dc_option(self) -> dict:
        """http.go parseDC + parseToken apply to WRITES as well as
        reads — splat this into every RPC write body so cross-DC
        forwarding and ACL enforcement engage (rpc.go:577)."""
        out: dict = {}
        if "dc" in self.query:
            out["dc"] = self.query["dc"]
        tok = self.token()
        if tok:
            out["token"] = tok
        return out

    def query_options(self) -> dict:
        """Blocking/consistency params → RPC body fields
        (http.go parseWait/parseConsistency)."""
        opts: dict = {}
        if "dc" in self.query:
            # http.go parseDC: target datacenter; the RPC layer forwards
            # over the WAN when it differs from the local DC.
            opts["dc"] = self.query["dc"]
        tok = self.token()
        if tok:
            opts["token"] = tok
        if "index" in self.query:
            opts["min_query_index"] = int(self.query["index"])
        if "wait" in self.query:
            opts["max_query_time"] = _parse_ttl(self.query["wait"])
        if self.flag("stale"):
            opts["allow_stale"] = True
        if self.flag("consistent"):
            opts["require_consistent"] = True
        return opts


class HTTPResponse:
    def __init__(self, status: int = 200, body: Any = None,
                 headers: Optional[dict] = None, raw: Optional[bytes] = None,
                 stream=None):
        self.status = status
        self.body = body
        self.headers = headers or {}
        self.raw = raw
        # Async iterator of bytes → Transfer-Encoding: chunked response
        # (the /v1/agent/monitor live feed).
        self.stream = stream


def _meta_headers(meta: Optional[dict]) -> dict:
    if not meta:
        return {}
    return {
        "X-Consul-Index": str(meta.get("index", 0)),
        "X-Consul-KnownLeader": "true" if meta.get("known_leader", True) else "false",
        "X-Consul-LastContact": str(int(meta.get("last_contact", 0))),
    }


class HTTPApi:
    """Routing + handlers (http.go:105-115 wrap/handle)."""

    def __init__(self, agent: Agent):
        self.agent = agent
        # (method, regex) -> handler(req, match) routes, first match wins.
        self.routes: list[tuple[str, re.Pattern, Callable]] = []
        self._route_buckets: dict[str, list] = {}
        self._register_routes()
        self._server: Optional[asyncio.AbstractServer] = None
        self.addr = ""

    # -- lifecycle ------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self._conn_tasks: set[asyncio.Task] = set()

        async def tracked(reader, writer):
            task = asyncio.current_task()
            self._conn_tasks.add(task)
            try:
                await self._handle_conn(reader, writer)
            finally:
                self._conn_tasks.discard(task)

        self._server = await asyncio.start_server(tracked, host, port)
        h, p = self._server.sockets[0].getsockname()[:2]
        self.addr = f"{h}:{p}"
        return self.addr

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            # Cancel in-flight handlers: a longpolling client (blocking
            # query, proxy config feed) would otherwise pin
            # wait_closed() for its full wait window.
            for task in list(getattr(self, "_conn_tasks", ())):
                task.cancel()
            try:
                await self._server.wait_closed()
            except Exception:  # noqa: BLE001
                pass

    # -- HTTP plumbing --------------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    break
                resp = await self._dispatch(req)
                await self._write_response(writer, req, resp, reader=reader)
                if req.headers.get("connection", "").lower() == "close":
                    break
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass
        except Exception:  # noqa: BLE001
            log.exception("http connection handler failed")
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass

    async def _read_request(self, reader) -> Optional[HTTPRequest]:
        # One readuntil for the whole head (request line + headers):
        # measurably faster than a readline loop on keep-alive
        # connections, where header parsing is per-request overhead.
        # CRLF line endings required (RFC 9112 §2.2 — bare-LF requests
        # are not recognized).
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except (ConnectionError, asyncio.IncompleteReadError,
                asyncio.LimitOverrunError):
            return None
        if not head:
            return None
        lines = head.decode("latin-1").split("\r\n")
        try:
            method, target, _version = lines[0].split()
        except ValueError:
            return None
        headers: dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            name, _, value = line.partition(":")
            headers[name.strip().lower()] = value.strip()
        body = b""
        if "content-length" in headers:
            body = await reader.readexactly(int(headers["content-length"]))
        path, _, qs = target.partition("?")
        query: dict[str, str] = {}
        if qs:
            if "%" not in qs and "+" not in qs:
                # Fast path: no percent/plus escapes to decode —
                # first-value-wins like parse_qs below.
                for part in qs.split("&"):
                    k, _, v = part.partition("=")
                    if k and k not in query:
                        query[k] = v
            else:
                query = {
                    k: v[0] for k, v in urllib.parse.parse_qs(
                        qs, keep_blank_values=True
                    ).items()
                }
        # Go's net/http serves the decoded URL.Path; %2F in a KV key
        # must reach the store as '/'.
        if "%" in path:
            path = urllib.parse.unquote(path)
        return HTTPRequest(method, path, query, headers, body)

    async def _write_response(self, writer, req: HTTPRequest,
                              resp: HTTPResponse, reader=None) -> None:
        if resp.stream is not None:
            return await self._write_chunked(writer, resp, reader)
        if resp.raw is not None:
            payload = resp.raw
            ctype = "application/octet-stream"
        else:
            out = camelize(resp.body)
            if req.query and req.flag("pretty"):
                payload = (json.dumps(out, indent=4) + "\n").encode()
            else:
                payload = (json.dumps(out, separators=(",", ":"))
                           + "\n").encode()
            ctype = "application/json"
        status_text = _STATUS_TEXT.get(resp.status, "OK")
        encoding = ""
        if (
            "gzip" in req.headers.get("accept-encoding", "")
            and len(payload) >= 256
        ):
            # http.go wraps handlers in gziphandler for the same cutoff
            # class of responses.
            payload = gzip.compress(payload)
            encoding = "gzip"
        # A handler-supplied Content-Type overrides the default (single
        # Content-Type per RFC 9110).
        extra = dict(resp.headers)
        ctype = extra.pop("Content-Type", ctype)
        head = [f"HTTP/1.1 {resp.status} {status_text}",
                f"Content-Type: {ctype}",
                f"Content-Length: {len(payload)}"]
        if encoding:
            head.append(f"Content-Encoding: {encoding}")
        for k, v in extra.items():
            head.append(f"{k}: {v}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + payload)
        await writer.drain()

    async def _write_chunked(self, writer, resp: HTTPResponse,
                             reader=None) -> None:
        """Stream an async byte iterator as a chunked response
        (agent_endpoint.go AgentMonitor's flushing writer).  The
        connection closes when the stream ends or the client hangs up —
        a live feed has no meaningful keep-alive continuation."""
        head = [f"HTTP/1.1 {resp.status} {_STATUS_TEXT.get(resp.status, 'OK')}",
                "Content-Type: "
                + resp.headers.get("Content-Type", "text/plain"),
                "Transfer-Encoding: chunked",
                "Connection: close"]
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode())
        await writer.drain()
        try:
            async for chunk in resp.stream:
                # Empty chunks are liveness ticks from the stream: a
                # cleanly-closed client delivers EOF on the read side
                # (a FIN alone never flips writer.is_closing), so check
                # the reader to tear down while the stream is quiet.
                if writer.is_closing() or (
                    reader is not None and reader.at_eof()
                ):
                    break
                if not chunk:
                    continue
                writer.write(f"{len(chunk):x}\r\n".encode()
                             + chunk + b"\r\n")
                await writer.drain()
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        finally:
            if hasattr(resp.stream, "aclose"):
                await resp.stream.aclose()
            writer.close()

    async def _dispatch(self, req: HTTPRequest) -> HTTPResponse:
        import time as _time

        metrics().incr_counter(f"http.{req.method}")
        _t0 = _time.monotonic()
        try:
            return await self._dispatch_inner(req)
        finally:
            metrics().measure_since("http.request", _t0)

    async def _dispatch_inner(self, req: HTTPRequest) -> HTTPResponse:
        path_matched = False
        bucket, catchall = self._route_candidates(req.path)
        for method, pattern, handler in (*bucket, *catchall):
            m = pattern.match(req.path)
            if not m:
                continue
            path_matched = True
            if method != req.method:
                continue
            try:
                resp = await handler(req, m)
                # ?filter= bexpr filtering on list results (http.go
                # parseFilter → go-bexpr), evaluated against the
                # camelized row shape the client sees.
                if "filter" in req.query and isinstance(resp.body, list):
                    from consul_tpu.agent.bexpr import create_filter

                    flt = create_filter(req.query["filter"])
                    resp.body = [
                        row
                        for row, crow in zip(resp.body, camelize(resp.body))
                        if flt.match(crow)
                    ]
                return resp
            except FilterError as e:
                return HTTPResponse(400, {"error": f"bad filter: {e}"})
            except RPCError as e:
                # http.go:1067-1080: ACL failures are 403s, the rest of
                # the RPC error space is a 500.
                msg = str(e)
                if msg in (ERR_PERMISSION_DENIED, ERR_ACL_NOT_FOUND):
                    return HTTPResponse(403, {"error": msg})
                return HTTPResponse(500, {"error": msg})
            except (ValueError, KeyError, json.JSONDecodeError) as e:
                return HTTPResponse(400, {"error": f"{type(e).__name__}: {e}"})
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                log.exception("handler error for %s %s", req.method, req.path)
                return HTTPResponse(500, {"error": str(e)})
        if path_matched:
            allowed = sorted({m for m, p, _ in self.routes if p.match(req.path)})
            return HTTPResponse(405, {"error": "method not allowed"},
                                headers={"Allow": ", ".join(allowed)})
        return HTTPResponse(404, {"error": f"no handler for {req.path}"})

    # -- route table (http_register.go) --------------------------------

    def _route(self, method: str, pattern: str, handler: Callable) -> None:
        compiled = re.compile(pattern + r"$")
        self.routes.append((method, compiled, handler))
        # Prefix-bucketed dispatch: the route table is ~100 entries and
        # a linear regex scan per request dominated the KV hot path
        # (~33 pattern.match calls/request).  Bucket by the static
        # "/v1/<segment>" prefix; dispatch looks up the bucket and scans
        # only its handful of candidates.  Routes whose second segment
        # is not static land in the catch-all bucket, always scanned.
        static = pattern
        for i, ch in enumerate(pattern):
            if ch in "([?*+.\\^$|{":
                static = pattern[:i]
                break
        parts = static.split("/")
        if static == pattern and len(parts) >= 3:
            key = "/".join(parts[:3])       # fully-literal route
        elif len(parts) >= 4:
            key = "/".join(parts[:3])       # second segment complete
        else:
            key = ""                        # dynamic early — always scan
        self._route_buckets.setdefault(key, []).append(
            (method, compiled, handler)
        )

    def _route_candidates(self, path: str):
        first = path.find("/", 1)
        second = path.find("/", first + 1) if first != -1 else -1
        key = path[:second] if second != -1 else path
        return self._route_buckets.get(key, ()), \
            self._route_buckets.get("", ())

    def _register_routes(self) -> None:
        r = self._route
        # UI (http.go handleUI when EnableUI; single-page here)
        r("GET", r"/ui(?:/.*)?", self.ui_index)
        r("GET", r"/", self.ui_redirect)
        # status
        r("GET", r"/v1/status/leader", self.status_leader)
        r("GET", r"/v1/status/peers", self.status_peers)
        # agent
        r("PUT", r"/v1/agent/force-leave/(?P<node>.+)",
          self.agent_force_leave)
        r("GET", r"/v1/agent/host", self.agent_host)
        r("GET", r"/v1/agent/metrics", self.agent_metrics)
        r("GET", r"/v1/agent/monitor", self.agent_monitor)
        r("GET", r"/v1/agent/self", self.agent_self)
        r("GET", r"/v1/agent/members", self.agent_members)
        r("GET", r"/v1/agent/segments", self.agent_segments)
        r("GET", r"/v1/agent/services", self.agent_services)
        r("GET", r"/v1/agent/service/(?P<sid>[^/?]+)", self.agent_service)
        r("GET", r"/v1/agent/checks", self.agent_checks)
        r("PUT", r"/v1/agent/join/(?P<addr>.+)", self.agent_join)
        r("PUT", r"/v1/agent/leave", self.agent_leave)
        r("PUT", r"/v1/agent/reload", self.agent_reload)
        r("PUT", r"/v1/agent/maintenance", self.agent_node_maintenance)
        r("PUT", r"/v1/agent/service/maintenance/(?P<sid>[^/?]+)",
          self.agent_service_maintenance)
        r("PUT", r"/v1/agent/service/register", self.agent_service_register)
        r("PUT", r"/v1/agent/service/deregister/(?P<sid>.+)",
          self.agent_service_deregister)
        r("PUT", r"/v1/agent/check/register", self.agent_check_register)
        r("PUT", r"/v1/agent/check/deregister/(?P<cid>.+)",
          self.agent_check_deregister)
        r("PUT", r"/v1/agent/check/pass/(?P<cid>.+)", self.agent_check_pass)
        r("PUT", r"/v1/agent/check/warn/(?P<cid>.+)", self.agent_check_warn)
        r("PUT", r"/v1/agent/check/fail/(?P<cid>.+)", self.agent_check_fail)
        # catalog
        r("GET", r"/v1/catalog/datacenters", self.catalog_datacenters)
        r("GET", r"/v1/catalog/nodes", self.catalog_nodes)
        r("GET", r"/v1/catalog/services", self.catalog_services)
        r("GET", r"/v1/catalog/service/(?P<svc>.+)", self.catalog_service)
        r("GET", r"/v1/catalog/node/(?P<node>.+)", self.catalog_node)
        r("PUT", r"/v1/catalog/register", self.catalog_register)
        r("PUT", r"/v1/catalog/deregister", self.catalog_deregister)
        # health
        r("GET", r"/v1/health/node/(?P<node>.+)", self.health_node)
        r("GET", r"/v1/health/checks/(?P<svc>.+)", self.health_checks)
        r("GET", r"/v1/health/service/(?P<svc>.+)", self.health_service)
        # /v1/health/connect/:service (health_endpoint.go
        # HealthConnectServiceNodes): proxies/native instances FOR the
        # service.
        r("GET", r"/v1/health/connect/(?P<svc>.+)", self.health_connect)
        r("GET", r"/v1/health/state/(?P<state>.+)", self.health_state)
        # kv
        r("GET", r"/v1/kv/(?P<key>.*)", self.kv_get)
        r("PUT", r"/v1/kv/(?P<key>.*)", self.kv_put)
        r("DELETE", r"/v1/kv/(?P<key>.*)", self.kv_delete)
        # sessions
        r("PUT", r"/v1/session/create", self.session_create)
        r("PUT", r"/v1/session/destroy/(?P<sid>.+)", self.session_destroy)
        r("PUT", r"/v1/session/renew/(?P<sid>.+)", self.session_renew)
        r("GET", r"/v1/session/info/(?P<sid>.+)", self.session_info)
        r("GET", r"/v1/session/node/(?P<node>.+)", self.session_node)
        r("GET", r"/v1/session/list", self.session_list)
        # events
        r("PUT", r"/v1/event/fire/(?P<name>.+)", self.event_fire)
        r("GET", r"/v1/event/list", self.event_list)
        # coordinates
        r("GET", r"/v1/coordinate/nodes", self.coordinate_nodes)
        r("GET", r"/v1/coordinate/node/(?P<node>.+)", self.coordinate_node)
        # prepared queries
        r("POST", r"/v1/query", self.query_create)
        r("GET", r"/v1/query/(?P<qid>[^/]+)/execute", self.query_execute)
        r("GET", r"/v1/query/(?P<qid>[^/]+)", self.query_get)
        r("PUT", r"/v1/query/(?P<qid>[^/]+)", self.query_update)
        r("DELETE", r"/v1/query/(?P<qid>[^/]+)", self.query_delete)
        r("GET", r"/v1/query", self.query_list)
        # txn
        r("PUT", r"/v1/txn", self.txn)
        # config entries
        r("PUT", r"/v1/config", self.config_apply)
        # CA rotation (the reference rotates via PUT /v1/connect/ca/
        # configuration provider/key changes; collapsed to an explicit
        # operator verb here).
        r("PUT", r"/v1/connect/ca/rotate", self.connect_ca_rotate)
        # federation states (http_register.go /v1/internal/federation-state*)
        r("GET", r"/v1/internal/federation-states/mesh-gateways",
          self.federation_state_mesh_gateways)
        r("GET", r"/v1/internal/federation-states",
          self.federation_state_list)
        r("GET", r"/v1/internal/federation-state/(?P<dc>[^/?]+)",
          self.federation_state_get)
        # discovery chain (discovery_chain_endpoint.go /v1/discovery-chain/)
        r("GET", r"/v1/discovery-chain/(?P<svc>[^/?]+)",
          self.discovery_chain_get)
        r("POST", r"/v1/discovery-chain/(?P<svc>[^/?]+)",
          self.discovery_chain_get)
        r("GET", r"/v1/config/(?P<kind>[^/]+)/(?P<name>.+)", self.config_get)
        r("GET", r"/v1/config/(?P<kind>[^/]+)", self.config_list)
        r("DELETE", r"/v1/config/(?P<kind>[^/]+)/(?P<name>.+)",
          self.config_delete)
        # operator
        r("GET", r"/v1/operator/raft/configuration", self.operator_raft)
        r("GET", r"/v1/operator/autopilot/health", self.operator_health)
        # connect (http_register.go /v1/connect/* + agent connect)
        r("GET", r"/v1/connect/ca/roots", self.connect_ca_roots)
        r("GET", r"/v1/agent/connect/ca/roots", self.connect_ca_roots)
        r("GET", r"/v1/agent/connect/ca/leaf/(?P<svc>.+)",
          self.connect_ca_leaf)
        r("POST", r"/v1/connect/intentions", self.intention_create)
        r("GET", r"/v1/connect/intentions/check", self.intention_check)
        r("GET", r"/v1/connect/intentions/(?P<iid>.+)", self.intention_get)
        r("GET", r"/v1/connect/intentions", self.intention_list)
        r("PUT", r"/v1/connect/intentions/(?P<iid>.+)", self.intention_update)
        r("DELETE", r"/v1/connect/intentions/(?P<iid>.+)",
          self.intention_delete)
        r("POST", r"/v1/agent/connect/authorize", self.connect_authorize)
        # Built-in proxy config feed (the xDS stand-in): blocking
        # snapshot reads per registered connect-proxy
        # (proxycfg/manager.go via agent_endpoint.go, re-designed as a
        # longpoll JSON endpoint instead of an Envoy gRPC stream).
        r("GET", r"/v1/agent/connect/proxy/(?P<pid>[^/?]+)/xds",
          self.connect_proxy_xds)
        r("GET", r"/v1/agent/connect/proxy/(?P<pid>[^/?]+)",
          self.connect_proxy_config)
        # autopilot (operator_autopilot_endpoint.go)
        r("GET", r"/v1/operator/autopilot/configuration",
          self.operator_autopilot_get)
        r("PUT", r"/v1/operator/autopilot/configuration",
          self.operator_autopilot_set)
        r("GET", r"/v1/operator/autopilot/health",
          self.operator_health)
        # keyring (operator_endpoint.go /v1/operator/keyring)
        r("GET", r"/v1/operator/keyring", self.keyring_list)
        r("POST", r"/v1/operator/keyring", self.keyring_install)
        r("PUT", r"/v1/operator/keyring", self.keyring_use)
        r("DELETE", r"/v1/operator/keyring", self.keyring_remove)
        # snapshot (http_register.go /v1/snapshot)
        r("GET", r"/v1/snapshot", self.snapshot_save)
        r("PUT", r"/v1/snapshot", self.snapshot_restore)
        # acl (http_register.go /v1/acl/*)
        r("PUT", r"/v1/acl/bootstrap", self.acl_bootstrap)
        r("PUT", r"/v1/acl/token", self.acl_token_set)
        r("GET", r"/v1/acl/tokens", self.acl_token_list)
        r("GET", r"/v1/acl/token/(?P<sid>.+)", self.acl_token_read)
        r("DELETE", r"/v1/acl/token/(?P<sid>.+)", self.acl_token_delete)
        r("PUT", r"/v1/acl/policy", self.acl_policy_set)
        r("GET", r"/v1/acl/policies", self.acl_policy_list)
        r("GET", r"/v1/acl/policy/(?P<pid>.+)", self.acl_policy_read)
        r("DELETE", r"/v1/acl/policy/(?P<pid>.+)", self.acl_policy_delete)
        # acl roles / auth methods / binding rules / login
        # (http_register.go /v1/acl/role*, /v1/acl/auth-method*,
        #  /v1/acl/binding-rule*, /v1/acl/login, /v1/acl/logout)
        r("PUT", r"/v1/acl/role", self.acl_role_set)
        r("GET", r"/v1/acl/roles", self.acl_role_list)
        r("GET", r"/v1/acl/role/name/(?P<name>.+)", self.acl_role_read_name)
        r("GET", r"/v1/acl/role/(?P<rid>.+)", self.acl_role_read)
        r("DELETE", r"/v1/acl/role/(?P<rid>.+)", self.acl_role_delete)
        r("PUT", r"/v1/acl/auth-method", self.acl_auth_method_set)
        r("GET", r"/v1/acl/auth-methods", self.acl_auth_method_list)
        r("GET", r"/v1/acl/auth-method/(?P<name>.+)",
          self.acl_auth_method_read)
        r("DELETE", r"/v1/acl/auth-method/(?P<name>.+)",
          self.acl_auth_method_delete)
        r("PUT", r"/v1/acl/binding-rule", self.acl_binding_rule_set)
        r("GET", r"/v1/acl/binding-rules", self.acl_binding_rule_list)
        r("GET", r"/v1/acl/binding-rule/(?P<rid>.+)",
          self.acl_binding_rule_read)
        r("DELETE", r"/v1/acl/binding-rule/(?P<rid>.+)",
          self.acl_binding_rule_delete)
        r("POST", r"/v1/acl/login", self.acl_login)
        r("POST", r"/v1/acl/logout", self.acl_logout)

    # -- helpers --------------------------------------------------------

    async def _acl_check(self, req: HTTPRequest, kind: str, name: str,
                         want: str) -> None:
        """Enforce one permission for agent-local HTTP operations.
        Server agents hold the resolver and check in-process; CLIENT
        agents resolve through their servers (consul/acl.go
        ResolveToken) via Internal.ACLAuthorize — without that hop the
        check would silently no-op exactly where keyring keys and
        force-leave live."""
        delegate = self.agent.delegate
        if hasattr(delegate, "acl_check"):
            delegate.acl_check({"token": req.token()}, kind, name, want)
        elif self.agent.config.acl_enabled:
            out = await self.agent.rpc("Internal.ACLAuthorize", {
                "kind": kind, "name": name, "want": want,
                "token": req.token(),
            })
            if not out.get("allowed"):
                raise RPCError(ERR_PERMISSION_DENIED)

    async def _rpc_read(self, req: HTTPRequest, method: str, body: dict,
                        key: str, unwrap_single: bool = False,
                        row: Optional[Callable] = None) -> HTTPResponse:
        body.update(req.query_options())
        out = await self.agent.rpc(method, body)
        meta = out.get("meta")
        data = out.get(key)
        if row is not None and data is not None:
            data = [row(r) for r in data]
        if unwrap_single:
            data = data[0] if data else None
            if data is None:
                return HTTPResponse(404, None, headers=_meta_headers(meta))
        return HTTPResponse(200, data, headers=_meta_headers(meta))

    async def agent_force_leave(self, req, m) -> HTTPResponse:
        # agent_endpoint.go:499 AgentForceLeave requires operator:write —
        # otherwise any caller can evict members.
        await self._acl_check(req, "operator", "", "write")
        ok = await self.agent.force_leave(m.group("node"))
        if not ok:
            return HTTPResponse(404, {"error": "member not failed"})
        return HTTPResponse(200, True)

    async def agent_host(self, req, m) -> HTTPResponse:
        """/v1/agent/host (agent/debug/host.go:20-40): platform info
        for the debug bundle."""
        import os
        import platform
        import sys as _sys
        import time as _time

        la = os.getloadavg() if hasattr(os, "getloadavg") else (0, 0, 0)
        return HTTPResponse(200, KeyedMap({
            "Host": KeyedMap({
                "Hostname": platform.node(),
                "OS": platform.system().lower(),
                "Platform": platform.platform(),
                "KernelArch": platform.machine(),
                "Uptime": _time.monotonic(),
            }),
            "CPU": KeyedMap({"Count": os.cpu_count(),
                             "LoadAvg": list(la)}),
            "Runtime": KeyedMap({"Python": _sys.version.split()[0]}),
            "CollectionTime": int(_time.time() * 1e9),
        }))

    async def agent_metrics(self, req, m) -> HTTPResponse:
        """/v1/agent/metrics (agent_endpoint.go AgentMetrics): the
        in-memory sink's aggregated view."""
        return HTTPResponse(200, KeyedMap(metrics().snapshot()))

    async def agent_monitor(self, req, m) -> HTTPResponse:
        """/v1/agent/monitor (agent_endpoint.go:1140 AgentMonitor):
        chunked stream of live log lines from the whole consul_tpu
        logger tree at ?loglevel= (default info)."""
        from consul_tpu.agent.monitor import Monitor

        # agent_endpoint.go AgentMonitor: requires agent:read.
        await self._acl_check(
            req, "agent", self.agent.config.node_name, "read")
        try:
            mon = Monitor(req.query.get("loglevel", "info")).start()
        except ValueError as e:
            return HTTPResponse(400, {"error": str(e)})

        async def lines():
            try:
                while True:
                    try:
                        yield await mon.next_line(timeout=5.0)
                    except asyncio.TimeoutError:
                        yield b""  # liveness tick → hang-up detection
            finally:
                dropped = mon.stop()
                if dropped:
                    log.warning("monitor dropped %d log lines", dropped)

        return HTTPResponse(200, stream=lines())

    async def ui_index(self, req, m) -> HTTPResponse:
        from consul_tpu.agent.ui import UI_HTML

        return HTTPResponse(
            200, None, raw=UI_HTML.encode(),
            headers={"Content-Type": "text/html; charset=utf-8"},
        )

    async def ui_redirect(self, req, m) -> HTTPResponse:
        return HTTPResponse(
            307, None, raw=b"", headers={"Location": "/ui"}
        )

    # -- status ---------------------------------------------------------

    async def status_leader(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("Status.Leader", {})
        return HTTPResponse(200, out.get("leader", ""))

    async def status_peers(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("Status.Peers", {})
        return HTTPResponse(200, [p["addr"] for p in out.get("peers", [])])

    # -- agent ----------------------------------------------------------

    async def agent_self(self, req, m) -> HTTPResponse:
        cfg = self.agent.config
        return HTTPResponse(200, {
            "config": {
                "datacenter": cfg.datacenter,
                "node_name": cfg.node_name,
                "server": cfg.server,
                "version": __version__,
            },
            "member": {
                "name": cfg.node_name,
                "addr": self.agent.serf.memberlist.transport.local_addr(),
                "tags": KeyedMap(self.agent.serf.config.tags),
            },
        })

    async def agent_members(self, req, m) -> HTTPResponse:
        # ?segment= filters one ring; ?segment=_all merges every ring a
        # server bridges (agent_endpoint.go AgentMembers segment param).
        segment = req.query.get("segment", "")
        delegate = self.agent.delegate
        if segment and hasattr(delegate, "segment_serfs"):
            if segment == "_all":
                rows = delegate._all_lan_members()
            else:
                seg = delegate.segment_serfs.get(segment)
                if seg is None:
                    return HTTPResponse(
                        404, {"error": f"unknown segment {segment!r}"})
                rows = list(seg.members.values())
        else:
            rows = list(self.agent.serf.members.values())
        members = [
            {
                "name": mem.name,
                "addr": mem.addr,
                # Serf tag names are data, not struct fields.
                "tags": KeyedMap(mem.tags),
                "status": int(mem.status),
            }
            for mem in rows
        ]
        return HTTPResponse(200, members)

    async def agent_segments(self, req, m) -> HTTPResponse:
        """GET /v1/agent/segments (operator segment listing)."""
        delegate = self.agent.delegate
        names = list(getattr(delegate, "segment_serfs", {}) or {})
        return HTTPResponse(200, [""] + names)

    async def agent_services(self, req, m) -> HTTPResponse:
        return HTTPResponse(200, KeyedMap({
            e.service["id"]: e.service for e in
            self.agent.local.services.values() if not e.deleted
        }))

    async def agent_reload(self, req, m) -> HTTPResponse:
        """PUT /v1/agent/reload (agent_endpoint.go AgentReload): re-read
        config sources, same path as SIGHUP.  Requires agent:write."""
        await self._acl_check(
            req, "agent", self.agent.config.node_name, "write")
        handler = getattr(self.agent, "reload_handler", None)
        if handler is None:
            return HTTPResponse(
                400, {"error": "agent has no reloadable config sources"})
        err = handler()
        if err is not None:
            # AgentReload returns the failure to the caller — a 200 on
            # a rejected config would leave the operator believing the
            # new config is live.
            return HTTPResponse(500, {"error": f"reload failed: {err}"})
        return HTTPResponse(200, True)

    async def agent_node_maintenance(self, req, m) -> HTTPResponse:
        """PUT /v1/agent/maintenance?enable=true|false&reason=...
        (agent_endpoint.go AgentNodeMaintenance)."""
        await self._acl_check(
            req, "node", self.agent.config.node_name, "write")
        enable = req.query.get("enable", "").lower()
        if enable not in ("true", "false"):
            return HTTPResponse(400, {"error": "missing ?enable=true|false"})
        if enable == "true":
            self.agent.enable_node_maintenance(req.query.get("reason", ""))
        else:
            self.agent.disable_node_maintenance()
        return HTTPResponse(200, True)

    async def agent_service_maintenance(self, req, m) -> HTTPResponse:
        """PUT /v1/agent/service/maintenance/:id?enable=...&reason=...
        (agent_endpoint.go AgentServiceMaintenance)."""
        sid = m.group("sid")
        # Lookup first, ACL with the REAL service name second (the
        # reference orders it the same way — a typo'd id is a 404, not
        # a spurious permission-denied on the empty name).
        entry = self.agent.local.services.get(sid)
        if entry is None or entry.deleted:
            return HTTPResponse(404, {"error": f"unknown service id {sid!r}"})
        await self._acl_check(
            req, "service", entry.service.get("service", ""), "write")
        enable = req.query.get("enable", "").lower()
        if enable not in ("true", "false"):
            return HTTPResponse(400, {"error": "missing ?enable=true|false"})
        if enable == "true":
            ok = self.agent.enable_service_maintenance(
                sid, req.query.get("reason", ""))
        else:
            ok = self.agent.disable_service_maintenance(sid)
        if not ok:
            return HTTPResponse(404, {"error": f"unknown service id {sid!r}"})
        return HTTPResponse(200, True)

    async def agent_service(self, req, m) -> HTTPResponse:
        """GET /v1/agent/service/:id (agent_endpoint.go AgentService) —
        one locally registered service, the agent_service watch's
        source."""
        entry = self.agent.local.services.get(m.group("sid"))
        if entry is None or entry.deleted:
            return HTTPResponse(404, {"error": "unknown service id"})
        return HTTPResponse(200, entry.service)

    async def agent_checks(self, req, m) -> HTTPResponse:
        return HTTPResponse(200, KeyedMap({
            e.check["check_id"]: e.check for e in
            self.agent.local.checks.values() if not e.deleted
        }))

    async def agent_join(self, req, m) -> HTTPResponse:
        n = await self.agent.join([m.group("addr")])
        return HTTPResponse(200, {"num_joined": n})

    async def agent_leave(self, req, m) -> HTTPResponse:
        await self.agent.leave()
        return HTTPResponse(200, {})

    async def agent_service_register(self, req, m) -> HTTPResponse:
        defn = _decamelize(req.json())
        checks = defn.pop("checks", None) or (
            [defn.pop("check")] if defn.get("check") else []
        )
        svc = {k: v for k, v in defn.items()
               if k in ("id", "service", "name", "tags", "port", "address",
                        "meta", "kind", "proxy", "connect_native")}
        if "name" in svc:
            svc["service"] = svc.pop("name")
        # Proxy block field spellings (structs.ConnectProxyConfig JSON):
        # DestinationServiceName is accepted as destination_service too.
        proxy = svc.get("proxy")
        if isinstance(proxy, dict) and "destination_service_name" in proxy:
            proxy = dict(proxy)
            proxy["destination_service"] = proxy.pop(
                "destination_service_name")
            svc["proxy"] = proxy
        self.agent.add_service(svc, checks)
        return HTTPResponse(200, {})

    async def agent_service_deregister(self, req, m) -> HTTPResponse:
        self.agent.remove_service(m.group("sid"))
        return HTTPResponse(200, {})

    async def agent_check_register(self, req, m) -> HTTPResponse:
        defn = _decamelize(req.json())
        if "name" in defn and "check_id" not in defn:
            defn["check_id"] = defn["name"]
        self.agent.add_check(defn)
        return HTTPResponse(200, {})

    async def agent_check_deregister(self, req, m) -> HTTPResponse:
        self.agent.remove_check(m.group("cid"))
        return HTTPResponse(200, {})

    async def _ttl_update(self, req, m, status: str) -> HTTPResponse:
        note = req.query.get("note", "")
        if not self.agent.update_ttl_check(m.group("cid"), status, note):
            return HTTPResponse(404, {"error": "unknown TTL check"})
        return HTTPResponse(200, {})

    async def agent_check_pass(self, req, m) -> HTTPResponse:
        return await self._ttl_update(req, m, "passing")

    async def agent_check_warn(self, req, m) -> HTTPResponse:
        return await self._ttl_update(req, m, "warning")

    async def agent_check_fail(self, req, m) -> HTTPResponse:
        return await self._ttl_update(req, m, "critical")

    # -- catalog ---------------------------------------------------------

    async def catalog_datacenters(self, req, m) -> HTTPResponse:
        try:
            out = await self.agent.rpc("Catalog.ListDatacenters", {})
        except RPCError:
            # No reachable server: answer with what we know locally.
            out = {}
        return HTTPResponse(200, out.get("datacenters") or
                            [self.agent.config.datacenter])

    async def catalog_nodes(self, req, m) -> HTTPResponse:
        return await self._rpc_read(req, "Catalog.ListNodes", {}, "nodes")

    async def catalog_services(self, req, m) -> HTTPResponse:
        body: dict = {}
        body.update(req.query_options())
        out = await self.agent.rpc("Catalog.ListServices", body)
        return HTTPResponse(200, KeyedMap(out.get("services") or {}),
                            headers=_meta_headers(out.get("meta")))

    def _service_node_row(self, r: dict) -> dict:
        """Internal service row → ``structs.ServiceNode`` JSON shape
        (camelized downstream: ServiceID/ServiceName/ServicePort/...)."""
        return {
            "id": "",
            "node": r.get("node", ""),
            "address": r.get("node_address", ""),
            "datacenter": self.agent.config.datacenter,
            "node_meta": KeyedMap(r.get("node_meta") or {}),
            "service_id": r.get("id", ""),
            "service_name": r.get("service", ""),
            "service_tags": r.get("tags") or [],
            "service_address": r.get("address", ""),
            "service_meta": KeyedMap(r.get("meta") or {}),
            "service_port": int(r.get("port") or 0),
            "create_index": r.get("create_index", 0),
            "modify_index": r.get("modify_index", 0),
        }

    def _check_service_node_row(self, r: dict) -> dict:
        """Internal health row → ``structs.CheckServiceNode`` JSON shape:
        {Node: {...}, Service: {...}, Checks: [...]}."""
        node = r.get("node") or {}
        svc = r.get("service") or {}
        return {
            "node": {
                "id": "",
                "node": node.get("node", svc.get("node", "")),
                "address": node.get("address", ""),
                "datacenter": self.agent.config.datacenter,
                "meta": KeyedMap(node.get("meta") or {}),
                "create_index": node.get("create_index", 0),
                "modify_index": node.get("modify_index", 0),
            },
            "service": {
                "id": svc.get("id", ""),
                "service": svc.get("service", ""),
                "tags": svc.get("tags") or [],
                "address": svc.get("address", ""),
                "meta": KeyedMap(svc.get("meta") or {}),
                "port": int(svc.get("port") or 0),
                "create_index": svc.get("create_index", 0),
                "modify_index": svc.get("modify_index", 0),
            },
            "checks": r.get("checks") or [],
        }

    async def catalog_service(self, req, m) -> HTTPResponse:
        body = {"service": m.group("svc")}
        if "tag" in req.query:
            body["tag"] = req.query["tag"]
        return await self._rpc_read(req, "Catalog.ServiceNodes", body, "nodes",
                                    row=self._service_node_row)

    async def catalog_node(self, req, m) -> HTTPResponse:
        return await self._rpc_read(
            req, "Internal.NodeInfo", {"node": m.group("node")}, "dump",
            unwrap_single=True,
        )

    async def catalog_register(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc(
            "Catalog.Register", {**_decamelize(req.json()), **req.dc_option()}
        )
        return HTTPResponse(200, out.get("result", True))

    async def catalog_deregister(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc(
            "Catalog.Deregister", {**_decamelize(req.json()), **req.dc_option()}
        )
        return HTTPResponse(200, out.get("result", True))

    # -- health ----------------------------------------------------------

    async def health_node(self, req, m) -> HTTPResponse:
        return await self._rpc_read(
            req, "Health.NodeChecks", {"node": m.group("node")}, "checks"
        )

    async def health_checks(self, req, m) -> HTTPResponse:
        return await self._rpc_read(
            req, "Health.ServiceChecks", {"service": m.group("svc")}, "checks"
        )

    async def health_service(self, req, m) -> HTTPResponse:
        body = {"service": m.group("svc"),
                "passing_only": req.flag("passing")}
        if "tag" in req.query:
            body["tag"] = req.query["tag"]
        return await self._rpc_read(req, "Health.ServiceNodes", body, "nodes",
                                    row=self._check_service_node_row)

    async def health_connect(self, req, m) -> HTTPResponse:
        body = {"service": m.group("svc"), "connect": True,
                "passing_only": req.flag("passing")}
        return await self._rpc_read(req, "Health.ServiceNodes", body, "nodes",
                                    row=self._check_service_node_row)

    async def health_state(self, req, m) -> HTTPResponse:
        return await self._rpc_read(
            req, "Health.ChecksInState", {"state": m.group("state")}, "checks"
        )

    # -- kv --------------------------------------------------------------

    async def kv_get(self, req, m) -> HTTPResponse:
        key = m.group("key")
        body: dict = {"key": key}
        body.update(req.query_options())
        if req.flag("keys"):
            body["separator"] = req.query.get("separator", "")
            out = await self.agent.rpc("KVS.ListKeys", body)
            return HTTPResponse(200, out.get("keys", []),
                                headers=_meta_headers(out.get("meta")))
        method = "KVS.List" if req.flag("recurse") else "KVS.Get"
        out = await self.agent.rpc(method, body)
        entries = out.get("entries", [])
        if not entries:
            return HTTPResponse(404, None,
                                headers=_meta_headers(out.get("meta")))
        if req.flag("raw") and not req.flag("recurse"):
            return HTTPResponse(200, None, raw=entries[0].get("value", b""),
                                headers=_meta_headers(out.get("meta")))
        return HTTPResponse(200, entries, headers=_meta_headers(out.get("meta")))

    async def kv_put(self, req, m) -> HTTPResponse:
        key = m.group("key")
        entry: dict = {"key": key, "value": req.body,
                       "flags": int(req.query.get("flags", 0))}
        if "acquire" in req.query:
            op = "lock"
            entry["session"] = req.query["acquire"]
        elif "release" in req.query:
            op = "unlock"
            entry["session"] = req.query["release"]
        elif "cas" in req.query:
            op = "cas"
            entry["modify_index"] = int(req.query["cas"])
        else:
            op = "set"
        out = await self.agent.rpc(
            "KVS.Apply", {"op": op, "entry": entry, **req.dc_option()}
        )
        result = out.get("result")
        return HTTPResponse(200, True if result is True or op == "set" else result)

    async def kv_delete(self, req, m) -> HTTPResponse:
        key = m.group("key")
        if req.flag("recurse"):
            body = {"op": "delete-tree", "entry": {"key": key}}
        elif "cas" in req.query:
            body = {"op": "delete-cas",
                    "entry": {"key": key,
                              "modify_index": int(req.query["cas"])}}
        else:
            body = {"op": "delete", "entry": {"key": key}}
        out = await self.agent.rpc("KVS.Apply", {**body, **req.dc_option()})
        result = out.get("result")
        return HTTPResponse(200, result if isinstance(result, bool) else True)

    # -- sessions ---------------------------------------------------------

    async def session_create(self, req, m) -> HTTPResponse:
        sess = _decamelize(req.json())
        sess.setdefault("node", self.agent.config.node_name)
        out = await self.agent.rpc(
            "Session.Apply",
            {"op": "create", "session": sess, **req.dc_option()},
        )
        return HTTPResponse(200, {"id": out["result"]})

    async def session_destroy(self, req, m) -> HTTPResponse:
        await self.agent.rpc("Session.Apply", {
            "op": "destroy", "session": {"id": m.group("sid")},
            **req.dc_option(),
        })
        return HTTPResponse(200, True)

    async def session_renew(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("Session.Renew", {"id": m.group("sid")})
        sessions = out.get("sessions", [])
        if not sessions:
            return HTTPResponse(404, {"error": "session not found"})
        return HTTPResponse(200, sessions)

    async def session_info(self, req, m) -> HTTPResponse:
        return await self._rpc_read(
            req, "Session.Get", {"id": m.group("sid")}, "sessions"
        )

    async def session_node(self, req, m) -> HTTPResponse:
        return await self._rpc_read(
            req, "Session.NodeSessions", {"node": m.group("node")}, "sessions"
        )

    async def session_list(self, req, m) -> HTTPResponse:
        return await self._rpc_read(req, "Session.List", {}, "sessions")

    # -- events -----------------------------------------------------------

    async def event_fire(self, req, m) -> HTTPResponse:
        # event_endpoint.go Fire: event write on the name.
        await self._acl_check(req, "event", m.group("name"), "write")
        eid = await self.agent.fire_event(m.group("name"), req.body)
        return HTTPResponse(200, {"id": eid, "name": m.group("name")})

    async def event_list(self, req, m) -> HTTPResponse:
        """Supports blocking on new events via ?index&wait
        (event_endpoint.go eventList long-poll)."""
        name = req.query.get("name")
        min_index = int(req.query.get("index", 0))
        if min_index:
            wait = _parse_ttl(req.query.get("wait", "")) or 300.0
            deadline = asyncio.get_running_loop().time() + wait
            while self.agent.event_index <= min_index:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    break
                handle = self.agent.event_wake_handle()
                try:
                    await asyncio.wait_for(handle.wait(), remaining)
                except asyncio.TimeoutError:
                    break
        events = [
            {"id": e.id, "name": e.name, "payload": e.payload,
             "l_time": e.ltime}
            for e in self.agent.events
            if name is None or e.name == name
        ]
        return HTTPResponse(
            200, events,
            headers={"X-Consul-Index": str(self.agent.event_index)},
        )

    # -- coordinates -------------------------------------------------------

    async def coordinate_nodes(self, req, m) -> HTTPResponse:
        return await self._rpc_read(req, "Coordinate.ListNodes", {},
                                    "coordinates")

    async def coordinate_node(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("Coordinate.Node",
                                   {"node": m.group("node")})
        coord = out.get("coord")
        if coord is None:
            return HTTPResponse(404, None)
        return HTTPResponse(200, [{"node": m.group("node"), "coord": coord}])

    # -- prepared queries ---------------------------------------------------

    async def query_create(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("PreparedQuery.Apply", {
            "op": "create", "query": _decamelize(req.json()),
        })
        return HTTPResponse(200, {"id": out["result"]})

    async def query_get(self, req, m) -> HTTPResponse:
        return await self._rpc_read(
            req, "PreparedQuery.Get", {"id": m.group("qid")}, "queries"
        )

    async def query_update(self, req, m) -> HTTPResponse:
        q = _decamelize(req.json())
        q["id"] = m.group("qid")
        await self.agent.rpc("PreparedQuery.Apply", {"op": "update", "query": q})
        return HTTPResponse(200, {})

    async def query_delete(self, req, m) -> HTTPResponse:
        await self.agent.rpc("PreparedQuery.Apply", {
            "op": "delete", "query": {"id": m.group("qid")},
        })
        return HTTPResponse(200, {})

    async def query_list(self, req, m) -> HTTPResponse:
        return await self._rpc_read(req, "PreparedQuery.List", {}, "queries")

    async def query_execute(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("PreparedQuery.Execute",
                                   {"query_id": m.group("qid")})
        if out.get("error"):
            return HTTPResponse(404, {"error": out["error"]})
        return HTTPResponse(200, {"nodes": out["nodes"],
                                  "service": out["service"]},
                            headers=_meta_headers(out.get("meta")))

    # -- txn ----------------------------------------------------------------

    async def txn(self, req, m) -> HTTPResponse:
        raw_ops = req.json()
        ops = []
        for op in raw_ops:
            op = _decamelize(op)
            kv = op.get("kv")
            if kv and isinstance(kv.get("value"), str):
                kv = dict(kv)
                kv_entry = {k: v for k, v in kv.items() if k != "verb"}
                kv_entry["value"] = base64.b64decode(kv["value"])
                op = {"kv": {"verb": kv["verb"], "entry": kv_entry}}
            elif kv and "entry" not in kv:
                op = {"kv": {"verb": kv.pop("verb"), "entry": kv}}
            # The API KVTxnOp carries the CAS index as ``Index``
            # (api/kv.go KVTxnOp); internally it's the modify_index.
            entry = op.get("kv", {}).get("entry")
            if entry and "index" in entry and "modify_index" not in entry:
                entry["modify_index"] = entry.pop("index")
            ops.append(op)
        out = await self.agent.rpc(
            "Txn.Apply", {"ops": ops, **req.dc_option()}
        )
        result = out.get("result", out)
        status = 200 if not result.get("errors") else 409
        return HTTPResponse(status, result)

    # -- config entries ------------------------------------------------------

    async def config_apply(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ConfigEntry.Apply", {
            "op": "set", "entry": _decamelize(req.json()),
            **req.dc_option(),
        })
        return HTTPResponse(200, out.get("result", True))

    async def config_get(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ConfigEntry.Get", {
            "kind": m.group("kind"), "name": m.group("name"),
            **req.query_options(),
        })
        if out.get("entry") is None:
            return HTTPResponse(404, None,
                                headers=_meta_headers(out.get("meta")))
        return HTTPResponse(200, out["entry"],
                            headers=_meta_headers(out.get("meta")))

    async def config_list(self, req, m) -> HTTPResponse:
        return await self._rpc_read(
            req, "ConfigEntry.List", {"kind": m.group("kind")}, "entries"
        )

    async def config_delete(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ConfigEntry.Apply", {
            "op": "delete",
            "entry": {"kind": m.group("kind"), "name": m.group("name")},
            **req.dc_option(),
        })
        return HTTPResponse(200, out.get("result", True))

    async def connect_ca_rotate(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ConnectCA.Rotate",
                                   {"token": req.token()})
        return HTTPResponse(200, {"root_id": out.get("root_id", "")})

    async def discovery_chain_get(self, req, m) -> HTTPResponse:
        """GET/POST /v1/discovery-chain/:service
        (agent/discovery_chain_endpoint.go); POST bodies carry compile
        overrides."""
        body = {"name": m.group("svc"), **req.query_options()}
        if req.method == "POST" and req.body:
            overrides = _decamelize(req.json())
            for k in ("override_protocol", "use_in_datacenter"):
                if overrides.get(k):
                    body[k] = overrides[k]
            if overrides.get("override_connect_timeout_s"):
                # Validate at the boundary: a malformed override is the
                # caller's 400, not a server-side 500.
                body["override_connect_timeout_s"] = float(
                    overrides["override_connect_timeout_s"])
        out = await self.agent.rpc("DiscoveryChain.Get", body)
        chain = out.get("chain") or {}
        # Node keys / target ids are DATA keys — shield them from
        # camelization (their values still camelize normally).
        chain = {**chain,
                 "nodes": KeyedMap(chain.get("nodes") or {}),
                 "targets": KeyedMap(chain.get("targets") or {})}
        return HTTPResponse(200, {"chain": chain},
                            headers=_meta_headers(out.get("meta")))

    # -- federation states ---------------------------------------------------

    async def federation_state_list(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc(
            "FederationState.List", dict(req.query_options())
        )
        return HTTPResponse(200, out.get("states", []),
                            headers=_meta_headers(out.get("meta")))

    async def federation_state_get(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("FederationState.Get", {
            "target_dc": m.group("dc"), **req.query_options(),
        })
        if out.get("state") is None:
            return HTTPResponse(404, {"error": "federation state not found"})
        return HTTPResponse(200, {"state": out["state"]},
                            headers=_meta_headers(out.get("meta")))

    async def federation_state_mesh_gateways(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc(
            "FederationState.ListMeshGateways", dict(req.query_options())
        )
        # DC names are data keys — keep them out of camelization.
        return HTTPResponse(200, KeyedMap(out.get("gateways", {})),
                            headers=_meta_headers(out.get("meta")))

    # -- connect -------------------------------------------------------------

    async def connect_ca_roots(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ConnectCA.Roots", dict(req.query_options()))
        roots = out.get("roots") or []
        return HTTPResponse(200, {
            "active_root_id": next(
                (r["id"] for r in roots if r.get("active")), ""
            ),
            "roots": roots,
        }, headers=_meta_headers(out.get("meta")))

    async def connect_ca_leaf(self, req, m) -> HTTPResponse:
        """GET /v1/agent/connect/ca/leaf/:service — cached per service
        like the reference's connect-ca-leaf cache type: re-signed only
        when the active root rotates or the cert passes half-life
        (cache-types/connect_ca_leaf.go), so repeated reads (and the
        connect_leaf watch) see a STABLE cert, not a fresh signature
        per request."""
        import datetime as _dt

        svc = m.group("svc")
        # agent_endpoint.go AgentConnectCALeafCert: service:write on the
        # named service — enforced per request, cached cert or not (the
        # cache must never bypass the ACL gate).
        await self._acl_check(req, "service", svc, "write")
        roots_out = await self.agent.rpc(
            "ConnectCA.Roots", dict(req.query_options()))
        active = next(
            (r["id"] for r in roots_out.get("roots") or []
             if r.get("active")), "")
        cache = getattr(self.agent, "_leaf_cache", None)
        if cache is None:
            cache = self.agent._leaf_cache = {}
        cache_key = (svc, req.query.get("dc", ""))
        leaf = cache.get(cache_key)
        stale = leaf is None or leaf.get("root_id") != active
        if leaf is not None and not stale:
            try:
                expires = _dt.datetime.fromisoformat(leaf["valid_before"])
                issued = _dt.datetime.fromisoformat(
                    leaf.get("valid_after", leaf["valid_before"]))
                life = (expires - issued).total_seconds()
                left = (expires - _dt.datetime.now(_dt.timezone.utc)
                        ).total_seconds()
                stale = life > 0 and left < life * 0.5
            except (KeyError, ValueError):
                stale = False
        if stale:
            # query_options() carries the caller's token — the Sign RPC
            # enforces its own ACL with it.
            out = await self.agent.rpc("ConnectCA.Sign", {
                "service": svc, **req.query_options(),
            })
            leaf = out.get("leaf")
            cache[cache_key] = leaf
        return HTTPResponse(200, leaf)

    async def intention_create(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("Intention.Apply", {
            "op": "create", "intention": _decamelize(req.json()),
            **req.dc_option(),
        })
        return HTTPResponse(200, {"id": out.get("result")})

    async def intention_update(self, req, m) -> HTTPResponse:
        intention = _decamelize(req.json())
        intention["id"] = m.group("iid")
        out = await self.agent.rpc("Intention.Apply", {
            "op": "update", "intention": intention, **req.dc_option(),
        })
        return HTTPResponse(200, bool(out.get("result")))

    async def intention_delete(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("Intention.Apply", {
            "op": "delete", "intention": {"id": m.group("iid")},
            **req.dc_option(),
        })
        return HTTPResponse(200, bool(out.get("result")))

    async def intention_get(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("Intention.Get", {
            "id": m.group("iid"), **req.query_options(),
        })
        rows = out.get("intentions") or []
        if not rows:
            return HTTPResponse(404, {"error": "intention not found"})
        return HTTPResponse(200, rows[0],
                            headers=_meta_headers(out.get("meta")))

    async def intention_list(self, req, m) -> HTTPResponse:
        return await self._rpc_read(req, "Intention.List", {}, "intentions")

    async def intention_check(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("Intention.Check", {
            "source": req.query.get("source", ""),
            "destination": req.query.get("destination", ""),
            **req.query_options(),
        })
        return HTTPResponse(200, {"allowed": out.get("allowed", False)})

    async def connect_authorize(self, req, m) -> HTTPResponse:
        """agent_endpoint.go AgentConnectAuthorize: a proxy presents the
        client cert's SPIFFE URI; authorization = intention check on
        (client service -> target service)."""
        body = _decamelize(req.json())
        target = body.get("target", "")
        uri = body.get("client_cert_uri", "")
        source = uri.rsplit("/svc/", 1)[-1] if "/svc/" in uri else uri
        out = await self.agent.rpc("Intention.Check", {
            "source": source, "destination": target, **req.dc_option(),
        })
        return HTTPResponse(200, {
            "authorized": out.get("allowed", False),
            "reason": out.get("reason", ""),
        })

    async def _proxy_snapshot(self, req, pid: str):
        """Shared longpoll fetch for the proxy-config feeds: honor
        ?index/?wait, wait out the first assembly of a just-registered
        proxy, None for unknown ids."""
        min_version = int(req.query.get("index", 0) or 0)
        wait = _parse_ttl(req.query.get("wait", "")) or 300.0
        if min_version > 0:
            return await self.agent.proxycfg.wait(
                pid, min_version=min_version, timeout=wait)
        out = self.agent.proxycfg.snapshot(pid)
        if out is None and pid in self.agent.proxycfg.proxy_ids():
            # Registered but not yet assembled: wait for the first.
            out = await self.agent.proxycfg.wait(pid, 0, timeout=wait)
        return out

    async def connect_proxy_config(self, req, m) -> HTTPResponse:
        """GET /v1/agent/connect/proxy/:proxy_id?index=N&wait=30s —
        the proxy's config snapshot, longpolling on its version."""
        pid = m.group("pid")
        out = await self._proxy_snapshot(req, pid)
        if out is None:
            return HTTPResponse(404, {"error": f"unknown proxy {pid!r}"})
        version, snap = out
        # Upstream maps are keyed by service names / target ids: data.
        shaped = {**snap,
                  "upstreams": KeyedMap({
                      name: {**up, "instances": KeyedMap(up["instances"])}
                      for name, up in snap["upstreams"].items()
                  })}
        return HTTPResponse(200, shaped,
                            headers={"X-Consul-Index": str(version)})

    async def connect_proxy_xds(self, req, m) -> HTTPResponse:
        """GET /v1/agent/connect/proxy/:proxy_id/xds?index=N&wait=30s —
        the ADS-shaped export of the same snapshot (agent/xds/server.go
        re-designed as a blocking JSON feed; each resource family keyed
        by its v2 type URL)."""
        from consul_tpu.connect import xds as xds_mod

        pid = m.group("pid")
        out = await self._proxy_snapshot(req, pid)
        if out is None:
            return HTTPResponse(404, {"error": f"unknown proxy {pid!r}"})
        version, snap = out
        public_port = int(req.query.get("port", 0) or 0)
        ads = xds_mod.ads_snapshot(snap, version, public_port=public_port)
        # The whole response is an Envoy-shaped wire structure
        # (DiscoveryResponse-style), not our struct fields — ship it
        # byte-exact, no camelization anywhere in the tree.
        return HTTPResponse(
            200, _raw_tree(ads),
            headers={"X-Consul-Index": str(version)},
        )

    # -- keyring -------------------------------------------------------------

    async def _keyring_op(self, req, op: str, need_key: bool) -> HTTPResponse:
        # internal_endpoint.go:414-422: list needs keyring:read, the
        # mutating ops keyring:write — without this an anonymous client
        # could read the live gossip keys.
        want = "read" if op == "list_keys" else "write"
        await self._acl_check(req, "keyring", "", want)
        key = ""
        if need_key:
            body = _decamelize(req.json())
            key = body.get("key", "")
            if not key:
                return HTTPResponse(400, {"error": "missing Key"})
        try:
            out = await self.agent.keyring_operation(op, key)
        except ValueError as e:
            return HTTPResponse(400, {"error": str(e)})
        # keys (base64) and errors (node names) are DATA keys: shield
        # them from camelization or they come back unusable.
        shaped = KeyedMap({
            label: {**res,
                    "keys": KeyedMap(res.get("keys", {})),
                    "errors": KeyedMap(res.get("errors", {}))}
            for label, res in out.items()
        })
        return HTTPResponse(200, shaped)

    async def keyring_list(self, req, m) -> HTTPResponse:
        return await self._keyring_op(req, "list_keys", need_key=False)

    async def keyring_install(self, req, m) -> HTTPResponse:
        return await self._keyring_op(req, "install_key", need_key=True)

    async def keyring_use(self, req, m) -> HTTPResponse:
        return await self._keyring_op(req, "use_key", need_key=True)

    async def keyring_remove(self, req, m) -> HTTPResponse:
        return await self._keyring_op(req, "remove_key", need_key=True)

    # -- snapshot ------------------------------------------------------------

    async def snapshot_save(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("Snapshot.Save", dict(req.query_options()))
        return HTTPResponse(
            200, None, raw=out.get("archive", b""),
            headers={"X-Consul-Index": str(out.get("index", 0)),
                     "Content-Type": "application/x-gzip"},
        )

    async def snapshot_restore(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("Snapshot.Restore", {
            "archive": req.body, **req.dc_option(),
        })
        return HTTPResponse(200, bool(out.get("result", True)))

    # -- acl -----------------------------------------------------------------

    async def acl_bootstrap(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.Bootstrap", req.dc_option())
        return HTTPResponse(200, out.get("token"))

    async def acl_token_set(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.TokenSet", {
            "acl_token": _decamelize(req.json()), **req.dc_option(),
        })
        return HTTPResponse(200, out.get("token"))

    async def acl_token_list(self, req, m) -> HTTPResponse:
        body = dict(req.query_options())
        out = await self.agent.rpc("ACL.TokenList", body)
        return HTTPResponse(200, out.get("tokens", []),
                            headers=_meta_headers(out.get("meta")))

    async def acl_token_read(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.TokenRead", {
            "secret_id": m.group("sid"), **req.query_options(),
        })
        if out.get("token") is None:
            return HTTPResponse(404, {"error": "token not found"})
        return HTTPResponse(200, out["token"])

    async def acl_token_delete(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.TokenDelete", {
            "secret_id": m.group("sid"), **req.dc_option(),
        })
        return HTTPResponse(200, bool(out.get("result", True)))

    async def acl_policy_set(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.PolicySet", {
            "policy": _decamelize(req.json()), **req.dc_option(),
        })
        return HTTPResponse(200, out.get("policy"))

    async def acl_policy_list(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.PolicyList", dict(req.query_options()))
        return HTTPResponse(200, out.get("policies", []),
                            headers=_meta_headers(out.get("meta")))

    async def acl_policy_read(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.PolicyRead", {
            "id": m.group("pid"), **req.query_options(),
        })
        if out.get("policy") is None:
            return HTTPResponse(404, {"error": "policy not found"})
        return HTTPResponse(200, out["policy"])

    async def acl_policy_delete(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.PolicyDelete", {
            "id": m.group("pid"), **req.dc_option(),
        })
        return HTTPResponse(200, bool(out.get("result", True)))

    async def acl_role_set(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.RoleSet", {
            "role": _decamelize(req.json()), **req.dc_option(),
        })
        return HTTPResponse(200, out.get("role"))

    async def acl_role_list(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.RoleList", dict(req.query_options()))
        return HTTPResponse(200, out.get("roles", []),
                            headers=_meta_headers(out.get("meta")))

    async def acl_role_read(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.RoleRead", {
            "id": m.group("rid"), **req.query_options(),
        })
        if out.get("role") is None:
            return HTTPResponse(404, {"error": "role not found"})
        return HTTPResponse(200, out["role"])

    async def acl_role_read_name(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.RoleRead", {
            "name": m.group("name"), **req.query_options(),
        })
        if out.get("role") is None:
            return HTTPResponse(404, {"error": "role not found"})
        return HTTPResponse(200, out["role"])

    async def acl_role_delete(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.RoleDelete", {
            "id": m.group("rid"), **req.dc_option(),
        })
        return HTTPResponse(200, bool(out.get("result", True)))

    async def acl_auth_method_set(self, req, m) -> HTTPResponse:
        raw = req.json()
        method = _decamelize(raw)
        # The Config subtree's claim-mapping keys are DATA (claim names
        # like "preferred_username"), not struct fields — rebuild them
        # from the raw JSON so case survives the snake/camel round-trip,
        # and mark them KeyedMap so responses leave them alone.
        cfg_raw = raw.get("Config") or raw.get("config") or {}
        if isinstance(cfg_raw, dict):
            cfg = {}
            for k, v in cfg_raw.items():
                sk = _snake_key(k)
                if sk in ("claim_mappings", "list_claim_mappings") \
                        and isinstance(v, dict):
                    v = KeyedMap(v)
                cfg[sk] = v
            method["config"] = cfg
        out = await self.agent.rpc("ACL.AuthMethodSet", {
            "auth_method": method, **req.dc_option(),
        })
        # Re-shield on the way out: the echoed record may have crossed
        # an RPC forward, which strips the KeyedMap marker.
        return HTTPResponse(
            200, _shield_claim_keys(out.get("auth_method") or {})
        )

    async def acl_auth_method_list(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc(
            "ACL.AuthMethodList", dict(req.query_options())
        )
        methods = [_shield_claim_keys(mth)
                   for mth in out.get("auth_methods", [])]
        return HTTPResponse(200, methods,
                            headers=_meta_headers(out.get("meta")))

    async def acl_auth_method_read(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.AuthMethodRead", {
            "name": m.group("name"), **req.query_options(),
        })
        if out.get("auth_method") is None:
            return HTTPResponse(404, {"error": "auth method not found"})
        return HTTPResponse(200, _shield_claim_keys(out["auth_method"]))

    async def acl_auth_method_delete(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.AuthMethodDelete", {
            "name": m.group("name"), **req.dc_option(),
        })
        return HTTPResponse(200, bool(out.get("result", True)))

    async def acl_binding_rule_set(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.BindingRuleSet", {
            "binding_rule": _decamelize(req.json()), **req.dc_option(),
        })
        return HTTPResponse(200, out.get("binding_rule"))

    async def acl_binding_rule_list(self, req, m) -> HTTPResponse:
        body = dict(req.query_options())
        if "authmethod" in req.query:
            body["auth_method"] = req.query["authmethod"]
        out = await self.agent.rpc("ACL.BindingRuleList", body)
        return HTTPResponse(200, out.get("binding_rules", []),
                            headers=_meta_headers(out.get("meta")))

    async def acl_binding_rule_read(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.BindingRuleRead", {
            "id": m.group("rid"), **req.query_options(),
        })
        if out.get("binding_rule") is None:
            return HTTPResponse(404, {"error": "binding rule not found"})
        return HTTPResponse(200, out["binding_rule"])

    async def acl_binding_rule_delete(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.BindingRuleDelete", {
            "id": m.group("rid"), **req.dc_option(),
        })
        return HTTPResponse(200, bool(out.get("result", True)))

    async def acl_login(self, req, m) -> HTTPResponse:
        # agent_endpoint.go ACLLogin: body carries AuthMethod +
        # BearerToken; no pre-existing token is required.
        out = await self.agent.rpc("ACL.Login", {
            "auth": _decamelize(req.json()), **req.dc_option(),
        })
        return HTTPResponse(200, out.get("token"))

    async def acl_logout(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("ACL.Logout", {
            **req.query_options(), **req.dc_option(),
        })
        return HTTPResponse(200, bool(out.get("result", True)))

    # -- operator ------------------------------------------------------------

    async def operator_raft(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("Operator.RaftGetConfiguration", {})
        return HTTPResponse(200, out)

    async def operator_health(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc("Operator.ServerHealth", {})
        return HTTPResponse(200, out)

    async def operator_autopilot_get(self, req, m) -> HTTPResponse:
        out = await self.agent.rpc(
            "Operator.AutopilotGetConfiguration",
            dict(req.query_options()))
        return HTTPResponse(200, out.get("config"))

    async def operator_autopilot_set(self, req, m) -> HTTPResponse:
        body = {"config": _decamelize(req.json()), **req.query_options()}
        if "cas" in req.query:
            body["cas"] = True
            body["modify_index"] = int(req.query["cas"])
        out = await self.agent.rpc(
            "Operator.AutopilotSetConfiguration", body)
        return HTTPResponse(200, bool(out.get("result", True)))


_CAMEL_SPLIT = re.compile(r"(?<=[a-z0-9])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])")


def _raw_tree(obj: Any) -> Any:
    """Recursively mark every dict as KeyedMap so camelize ships the
    structure byte-exact (Envoy-shaped xDS resources use their own
    snake_case wire names)."""
    if isinstance(obj, dict):
        return KeyedMap({k: _raw_tree(v) for k, v in obj.items()})
    if isinstance(obj, list):
        return [_raw_tree(v) for v in obj]
    return obj


def _shield_claim_keys(method: dict) -> dict:
    """Re-mark an auth method's claim-mapping keys as data before the
    response camelizes.  The KeyedMap wrapper applied at write time does
    not survive raft replication or a snapshot round-trip (it serializes
    as a plain dict), so reads re-apply it."""
    cfg = method.get("config")
    if not isinstance(cfg, dict):
        return method
    cfg = dict(cfg)
    for k in ("claim_mappings", "list_claim_mappings"):
        if isinstance(cfg.get(k), dict):
            cfg[k] = KeyedMap(cfg[k])
    return {**method, "config": cfg}


def _decamelize(obj: Any) -> Any:
    """CamelCase request JSON → snake_case bodies; ID/TTL handled."""
    if isinstance(obj, dict):
        return {_snake_key(k): _decamelize(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_decamelize(v) for v in obj]
    return obj


def _snake_key(key: str) -> str:
    for acro, camel in (("ID", "Id"), ("TTL", "Ttl"), ("DNS", "Dns"),
                        ("HTTP", "Http"), ("TCP", "Tcp")):
        key = key.replace(acro, camel)
    return _CAMEL_SPLIT.sub("_", key).lower()
