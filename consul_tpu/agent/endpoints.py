"""Server RPC endpoint services.

Equivalent of the reference's ``agent/consul/*_endpoint.go`` files,
registered like ``server_oss.go:8-23``.  Every method takes the msgpack
request body and returns a msgpack-friendly dict; reads run through
``blocking_query`` and return ``{"meta": QueryMeta, ...}``; writes
forward to the leader and apply through raft.

Wire method names match the reference (``KVS.Apply``,
``Health.ServiceNodes``, ``Catalog.NodeServices`` ...) so a client of
the reference finds the same RPC surface.
"""

from __future__ import annotations

import asyncio
import time
import uuid
from typing import TYPE_CHECKING, Any, Callable, Optional

from consul_tpu.acl.engine import READ, WRITE
from consul_tpu.agent.fsm import MessageType
from consul_tpu.agent.rpc import (
    ERR_PERMISSION_DENIED,
    QueryOptions,
    RPCError,
    blocking_query,
)
from consul_tpu.store.state import HEALTH_CRITICAL, HEALTH_PASSING

if TYPE_CHECKING:
    from consul_tpu.agent.server import Server


class _Endpoint:
    def __init__(self, server: "Server"):
        self.server = server

    def _authz(self, body: dict):
        """Authorizer for read-side filterACL, or None when enforcement
        is off (ACLs disabled, or the request targets another DC whose
        own servers enforce)."""
        srv = self.server
        if not srv.acl.enabled:
            return None
        dc = body.get("dc")
        if dc and dc != srv.config.datacenter:
            return None
        return srv.acl_resolve(body)

    async def _read(self, method: str, body: dict, run: Callable):
        """Common read path: forward unless stale, optional consistency
        barrier, then blocking query (rpc.go blockingQuery users)."""
        fwd = await self.server.forward(method, body, read=True)
        if fwd is not None:
            return fwd
        opts = QueryOptions.from_body(body)
        if opts.require_consistent:
            await self.server.consistent_barrier()
        meta, result = await blocking_query(self.server.store, opts, run)
        out = {"meta": meta.to_body()}
        out.update(result if isinstance(result, dict) else {})
        return out

    async def _write(self, method: str, msg_type: MessageType, body: dict):
        fwd = await self.server.forward(method, body)
        if fwd is not None:
            return fwd
        result = await self.server.raft_apply(msg_type, body)
        return {"result": result, "index": self.server.store.max_index(
            *_TABLES_BY_TYPE.get(msg_type, ("index",)))}


_TABLES_BY_TYPE = {
    MessageType.REGISTER: ("nodes", "services", "checks"),
    MessageType.DEREGISTER: ("nodes", "services", "checks"),
    MessageType.KVS: ("kvs", "tombstones"),
    MessageType.SESSION: ("sessions",),
    MessageType.TXN: ("kvs", "tombstones"),
    MessageType.PREPARED_QUERY: ("prepared_queries",),
    MessageType.CONFIG_ENTRY: ("config_entries",),
}


class Status(_Endpoint):
    """status_endpoint.go — cluster metadata, never forwarded."""

    async def ping(self, body: dict) -> bool:
        return True

    async def leader(self, body: dict) -> dict:
        return {"leader": self.server.leader_rpc_addr() or ""}

    async def peers(self, body: dict) -> dict:
        raft = self.server.raft
        peers = []
        if raft is not None:
            for vid in raft.voters:
                addr = self.server._raft_peer_addr(vid)
                peers.append({"id": vid, "addr": addr or ""})
        return {"peers": peers}


class Catalog(_Endpoint):
    """catalog_endpoint.go."""

    async def register(self, body: dict):
        # catalog_endpoint.go Register: node write + service write when
        # a service is included (vetRegisterWithACL).
        self.server.acl_check(body, "node", body.get("node", ""), WRITE)
        svc = body.get("service")
        if svc:
            self.server.acl_check(
                body, "service", svc.get("service", ""), WRITE
            )
        return await self._write("Catalog.Register", MessageType.REGISTER, body)

    async def deregister(self, body: dict):
        self.server.acl_check(body, "node", body.get("node", ""), WRITE)
        return await self._write("Catalog.Deregister", MessageType.DEREGISTER, body)

    async def list_nodes(self, body: dict):
        out = await self._read(
            "Catalog.ListNodes", body,
            lambda ws: _wrap(self.server.store.nodes(ws), "nodes"),
        )
        authz = self._authz(body)
        if authz is not None and "nodes" in out:
            out["nodes"] = [
                n for n in out["nodes"] if authz.node_read(n.get("name", ""))
            ]
        return out

    async def service_dump(self, body: dict):
        """All service instances + node join (internal ServiceDump —
        the DNS PTR index and debug consumers)."""
        out = await self._read(
            "Catalog.ServiceDump", body,
            lambda ws: _wrap(
                self.server.store.service_dump(ws=ws), "services"),
        )
        authz = self._authz(body)
        if authz is not None and "services" in out:
            out["services"] = [
                s for s in out["services"]
                if authz.service_read(s.get("service", ""))
            ]
        return out

    async def service_kind_nodes(self, body: dict):
        """Instances of a service KIND — mesh-gateway discovery for the
        data plane (catalog_endpoint.go ServiceNodes with ServiceKind /
        internal ServiceDump kind filter)."""
        out = await self._read(
            "Catalog.ServiceKindNodes", body,
            lambda ws: _wrap(
                self.server.store.services_by_kind(
                    body.get("kind", ""),
                    passing_only=bool(body.get("passing_only", False)),
                    ws=ws),
                "nodes",
            ),
        )
        authz = self._authz(body)
        if authz is not None and "nodes" in out:
            out["nodes"] = [
                n for n in out["nodes"]
                if authz.service_read(n.get("service", ""))
            ]
        return out

    async def list_services(self, body: dict):
        out = await self._read(
            "Catalog.ListServices", body,
            lambda ws: _wrap(self.server.store.services(ws), "services"),
        )
        authz = self._authz(body)
        if authz is not None and "services" in out:
            out["services"] = {
                name: tags
                for name, tags in out["services"].items()
                if authz.service_read(name)
            }
        return out

    async def service_nodes(self, body: dict):
        self.server.acl_check(body, "service", body.get("service", ""), READ)
        tag = body.get("tag")
        return await self._read(
            "Catalog.ServiceNodes", body,
            lambda ws: _wrap(
                self.server.store.service_nodes(body["service"], tag=tag, ws=ws),
                "nodes",
            ),
        )

    async def node_services(self, body: dict):
        self.server.acl_check(body, "node", body.get("node", ""), READ)
        out = await self._read(
            "Catalog.NodeServices", body,
            lambda ws: _wrap(
                self.server.store.node_services(body["node"], ws=ws), "services"
            ),
        )
        authz = self._authz(body)
        if authz is not None and "services" in out:
            out["services"] = [
                s for s in out["services"]
                if authz.service_read(s.get("service", ""))
            ]
        return out

    async def list_datacenters(self, body: dict):
        """catalog_endpoint.go ListDatacenters: known DCs sorted by
        estimated round-trip from here (router.go:534)."""
        return {"datacenters": self.server.router.get_datacenters_by_distance()}


class Health(_Endpoint):
    """health_endpoint.go."""

    async def node_checks(self, body: dict):
        self.server.acl_check(body, "node", body.get("node", ""), READ)
        return await self._read(
            "Health.NodeChecks", body,
            lambda ws: _wrap(self.server.store.node_checks(body["node"], ws=ws),
                             "checks"),
        )

    async def service_checks(self, body: dict):
        self.server.acl_check(body, "service", body.get("service", ""), READ)
        return await self._read(
            "Health.ServiceChecks", body,
            lambda ws: _wrap(
                self.server.store.service_checks(body["service"], ws=ws), "checks"
            ),
        )

    async def checks_in_state(self, body: dict):
        return await self._read(
            "Health.ChecksInState", body,
            lambda ws: _wrap(
                self.server.store.checks_in_state(body["state"], ws=ws), "checks"
            ),
        )

    async def service_nodes(self, body: dict):
        """Nodes + service + checks, optionally only passing instances
        (health_endpoint.go ServiceNodes w/ PassingOnly)."""
        self.server.acl_check(body, "service", body.get("service", ""), READ)
        passing = bool(body.get("passing_only", body.get("passing", False)))
        return await self._read(
            "Health.ServiceNodes", body,
            lambda ws: _wrap(
                self.server.store.check_service_nodes(
                    body["service"], tag=body.get("tag"),
                    passing_only=passing,
                    connect=bool(body.get("connect")), ws=ws,
                ),
                "nodes",
            ),
        )


class KVS(_Endpoint):
    """kvs_endpoint.go."""

    async def apply(self, body: dict):
        # kvs_endpoint.go:35-60 kvsPreApply: key write (+ the reference
        # also checks session perms for lock ops via the session's node).
        # delete-tree needs write over the ENTIRE subtree
        # (acl.KeyWritePrefix) — a plain longest-prefix check on the
        # prefix would let a parent-level token wipe a denied child.
        key = (body.get("entry") or {}).get("key", "")
        if body.get("op") == "delete-tree":
            self.server.acl_check(body, "key", key, WRITE,
                                  whole_subtree=True)
        else:
            self.server.acl_check(body, "key", key, WRITE)
        fwd = await self.server.forward("KVS.Apply", body)
        if fwd is not None:
            return fwd
        if body.get("op") == "lock":
            # Lock-delay is wall-time, so it is enforced pre-commit with
            # the leader's clock only — doing it in the FSM would let
            # peers diverge (kvs_endpoint.go:67-82 kvsPreApply).
            key = (body.get("entry") or {}).get("key", "")
            if self.server.store.kv_lock_delay(key) > 0:
                return {
                    "result": False,
                    "index": self.server.store.max_index("kvs", "tombstones"),
                }
        result = await self.server.raft_apply(MessageType.KVS, body)
        return {
            "result": result,
            "index": self.server.store.max_index("kvs", "tombstones"),
        }

    async def get(self, body: dict):
        self.server.acl_check(body, "key", body["key"], READ)

        def run(ws):
            idx, rec = self.server.store.kv_get(body["key"], ws=ws)
            return idx, {"entries": [rec] if rec else []}

        return await self._read("KVS.Get", body, run)

    async def list(self, body: dict):
        out = await self._read(
            "KVS.List", body,
            lambda ws: _wrap(self.server.store.kv_list(body["key"], ws=ws),
                             "entries"),
        )
        return self._filter_keys(body, out, "entries", lambda e: e["key"])

    async def list_keys(self, body: dict):
        out = await self._read(
            "KVS.ListKeys", body,
            lambda ws: _wrap(
                self.server.store.kv_keys(
                    body["key"], body.get("separator", ""), ws=ws
                ),
                "keys",
            ),
        )
        return self._filter_keys(body, out, "keys", lambda k: k)

    def _filter_keys(self, body: dict, out: dict, field: str, key_of):
        """filterACL on list results: entries the token cannot read are
        dropped, not denied (consul/filter.go FilterKeys)."""
        authz = self._authz(body)
        if authz is not None and field in out:
            out[field] = [
                item for item in out[field] if authz.key_read(key_of(item))
            ]
        return out


class Session(_Endpoint):
    """session_endpoint.go."""

    async def apply(self, body: dict):
        op = body.get("op")
        # session_endpoint.go Apply: session write on the session's node.
        node = (body.get("session") or {}).get("node", "")
        if op == "destroy" and not node:
            _, existing = self.server.store.session_get(
                (body.get("session") or {}).get("id", "")
            )
            node = (existing or {}).get("node", "")
        self.server.acl_check(body, "session", node, WRITE)
        if op == "create":
            sess = dict(body.get("session") or {})
            sess.setdefault("id", str(uuid.uuid4()))
            body = {"op": "create", "session": sess}
        out = await self._write("Session.Apply", MessageType.SESSION, body)
        return out

    async def get(self, body: dict):
        def run(ws):
            idx, rec = self.server.store.session_get(body["id"], ws=ws)
            return idx, {"sessions": [rec] if rec else []}

        out = await self._read("Session.Get", body, run)
        return self._filter_sessions(body, out)

    async def list(self, body: dict):
        out = await self._read(
            "Session.List", body,
            lambda ws: _wrap(self.server.store.session_list(ws=ws), "sessions"),
        )
        return self._filter_sessions(body, out)

    async def node_sessions(self, body: dict):
        out = await self._read(
            "Session.NodeSessions", body,
            lambda ws: _wrap(
                self.server.store.node_sessions(body["node"], ws=ws), "sessions"
            ),
        )
        return self._filter_sessions(body, out)

    def _filter_sessions(self, body: dict, out: dict) -> dict:
        """filterACL session:read per session's node (consul/filter.go
        FilterSessions): unreadable sessions drop out of lists."""
        authz = self._authz(body)
        if authz is not None and "sessions" in out:
            out["sessions"] = [
                s for s in out["sessions"]
                if authz.session_read(s.get("node", ""))
            ]
        return out

    async def renew(self, body: dict):
        fwd = await self.server.forward("Session.Renew", body)
        if fwd is not None:
            return fwd
        idx, sess = self.server.store.session_get(body["id"])
        if sess is None:
            return {"sessions": [], "meta": {"index": idx}}
        # session_endpoint.go Renew: session write on the session's node
        # (an unauthorized party must not keep locks alive).
        self.server.acl_check(body, "session", sess.get("node", ""), WRITE)
        from consul_tpu.agent.server import _parse_ttl

        ttl = _parse_ttl(sess.get("ttl"))
        if ttl > 0:
            self.server.renew_session(sess["id"], ttl)
        return {"sessions": [sess], "meta": {"index": idx}}


class Coordinate(_Endpoint):
    """coordinate_endpoint.go — updates are batched on the leader and
    flushed as one raft entry per CoordinateUpdatePeriod."""

    async def update(self, body: dict):
        # coordinate_endpoint.go Update: node write on the subject node.
        self.server.acl_check(body, "node", body.get("node", ""), WRITE)
        fwd = await self.server.forward("Coordinate.Update", body)
        if fwd is not None:
            return fwd
        self.server.stage_coordinate_update(
            body["node"], body.get("segment", ""), body["coord"]
        )
        return {"queued": True}

    async def list_nodes(self, body: dict):
        return await self._read(
            "Coordinate.ListNodes", body,
            lambda ws: _wrap(self.server.store.coordinates(ws=ws), "coordinates"),
        )

    async def node(self, body: dict):
        def run(ws):
            idx, _ = self.server.store.coordinates(ws=ws)
            coord = self.server.store.coordinate(
                body["node"], body.get("segment", "")
            )
            return idx, {"coord": coord}

        return await self._read("Coordinate.Node", body, run)


class Txn(_Endpoint):
    """txn_endpoint.go — read-only op sets skip raft (Txn.Read)."""

    def _check_txn_acls(self, body: dict, write: bool) -> None:
        """txn_endpoint.go Apply/Read vet each op's key against the
        token (the single-op KV enforcement must not be bypassable
        through /v1/txn)."""
        for op in body.get("ops") or []:
            kv = op.get("kv") if isinstance(op, dict) else None
            if not kv:
                continue
            key = (kv.get("entry") or {}).get("key", "")
            verb = kv.get("verb", "")
            want = READ if (not write or verb in ("get", "get-tree",
                                                  "check-index",
                                                  "check-session")) else WRITE
            self.server.acl_check(body, "key", key, want,
                                  whole_subtree=(verb == "delete-tree"))

    async def apply(self, body: dict):
        self._check_txn_acls(body, write=True)
        fwd = await self.server.forward("Txn.Apply", body)
        if fwd is not None:
            return fwd
        # Per-op pre-apply checks run with the leader's clock, exactly
        # like the single-op path (txn_endpoint.go Apply → kvsPreApply):
        # a "lock" verb inside a txn must honor lock-delay windows too.
        errors = []
        for i, op in enumerate(body.get("ops") or []):
            kv = op.get("kv") if isinstance(op, dict) else None
            if kv and kv.get("verb") == "lock":
                key = (kv.get("entry") or {}).get("key", "")
                if self.server.store.kv_lock_delay(key) > 0:
                    errors.append(
                        {"op_index": i,
                         "what": f"key {key!r} is under a lock-delay"}
                    )
        if errors:
            return {
                "result": {"results": [], "errors": errors},
                "index": self.server.store.max_index("kvs", "tombstones"),
            }
        result = await self.server.raft_apply(MessageType.TXN, body)
        return {
            "result": result,
            "index": self.server.store.max_index("kvs", "tombstones"),
        }

    async def read(self, body: dict):
        self._check_txn_acls(body, write=False)
        fwd = await self.server.forward("Txn.Read", body, read=True)
        if fwd is not None:
            return fwd
        results, errors = self.server.store.txn_read(body["ops"])
        return {"results": results, "errors": errors}


class ConfigEntry(_Endpoint):
    """config_endpoint.go."""

    async def apply(self, body: dict):
        # config_endpoint.go Apply checks per-kind service/operator
        # perms; collapsed here to operator write.
        self.server.acl_check(body, "operator", "", WRITE)
        return await self._write("ConfigEntry.Apply", MessageType.CONFIG_ENTRY, body)

    async def get(self, body: dict):
        def run(ws):
            idx, rec = self.server.store.config_entry_get(
                body["kind"], body["name"], ws=ws
            )
            return idx, {"entry": rec}

        return await self._read("ConfigEntry.Get", body, run)

    async def list(self, body: dict):
        return await self._read(
            "ConfigEntry.List", body,
            lambda ws: _wrap(
                self.server.store.config_entries_by_kind(body.get("kind"), ws=ws),
                "entries",
            ),
        )


class PreparedQuery(_Endpoint):
    """prepared_query_endpoint.go — execute resolves the query into a
    health-filtered node list (RTT ordering lands with the coordinate
    work in consul_tpu.models.vivaldi)."""

    async def apply(self, body: dict):
        op = body.get("op")
        # prepared_query_endpoint.go Apply: query write on the name.
        self.server.acl_check(
            body, "query", (body.get("query") or {}).get("name", ""), WRITE
        )
        if op in ("create", "update"):
            q = dict(body.get("query") or {})
            q.setdefault("id", str(uuid.uuid4()))
            body = {"op": op, "query": q}
        return await self._write(
            "PreparedQuery.Apply", MessageType.PREPARED_QUERY, body
        )

    async def get(self, body: dict):
        def run(ws):
            idx, rec = self.server.store.prepared_query_get(body["id"], ws=ws)
            return idx, {"queries": [rec] if rec else []}

        return await self._read("PreparedQuery.Get", body, run)

    async def list(self, body: dict):
        return await self._read(
            "PreparedQuery.List", body,
            lambda ws: _wrap(self.server.store.prepared_query_list(ws=ws),
                             "queries"),
        )

    async def execute(self, body: dict):
        fwd = await self.server.forward("PreparedQuery.Execute", body, read=True)
        if fwd is not None:
            return fwd
        if body.get("query") is not None:
            # ExecuteRemote (prepared_query_endpoint.go:480): another
            # DC's server shipped us the full query — queries are
            # per-DC state, so failover carries the definition.
            query = body["query"]
        else:
            query = self.server.store.prepared_query_resolve(body["query_id"])
        if query is None:
            return {"nodes": [], "service": "", "error": "query not found"}
        service = query["service"]["service"]
        idx, rows = self.server.store.check_service_nodes(service)
        only_passing = bool(query["service"].get("only_passing", False))
        out = []
        for r in rows:
            bad = [c for c in r["checks"] if c["status"] == HEALTH_CRITICAL]
            if bad:
                continue
            if only_passing and any(
                c["status"] != HEALTH_PASSING for c in r["checks"]
            ):
                continue
            out.append(r)
        limit = int(query.get("limit", 0) or body.get("limit", 0) or 0)
        if limit:
            out = out[:limit]
        if not out and body.get("query") is None:
            remote = await self._execute_failover(query, body, limit)
            if remote is not None:
                return remote
        return {"nodes": out, "service": service, "meta": {"index": idx}}

    async def _execute_failover(
        self, query: dict, body: dict, limit: int
    ) -> Optional[dict]:
        """RTT-ranked cross-DC failover (prepared_query_endpoint.go
        ExecuteRemote + queryFailover): when the local DC has no healthy
        instances, walk the failover DCs — nearest_n by Vivaldi distance
        over the WAN pool (router.go:534 GetDatacentersByDistance),
        then any explicitly listed DCs — and return the first DC that
        answers with instances."""
        failover = (query.get("service") or {}).get("failover") or {}
        nearest_n = int(failover.get("nearest_n", 0) or 0)
        explicit = list(failover.get("datacenters") or ())
        if nearest_n <= 0 and not explicit:
            return None
        ordered: list[str] = []
        by_distance = [
            dc for dc in self.server.router.get_datacenters_by_distance()
            if dc != self.server.config.datacenter
        ]
        ordered.extend(by_distance[:nearest_n])
        for dc in explicit:
            if dc not in ordered and dc != self.server.config.datacenter:
                ordered.append(dc)
        for dc in ordered:
            try:
                out = await self.server._forward_dc(
                    "PreparedQuery.Execute",
                    {"query": query, "query_id": body.get("query_id", ""),
                     "limit": limit, "dc": dc,
                     "token": body.get("token", "")},
                    dc,
                )
            except Exception:  # noqa: BLE001 - next DC
                continue
            if out and out.get("nodes"):
                out["datacenter"] = dc
                out["failovers"] = ordered.index(dc) + 1
                return out
        return None


class Internal(_Endpoint):
    """internal_endpoint.go — composite reads used by the UI/agent."""

    async def acl_authorize(self, body: dict):
        """Token → one permission verdict, for CLIENT agents that hold
        no resolver of their own (consul/acl.go ResolveToken resolves
        through servers from clients; collapsed to a single yes/no RPC
        instead of shipping policy documents).  Answered by ANY server —
        ACL tables are replicated state, so no leader forward (losing
        the leader must not take client-side permission checks down)."""
        from consul_tpu.acl.engine import PREFIX_RESOURCES, SCALAR_RESOURCES

        kind = body.get("kind", "")
        want = body.get("want", "")
        if (kind not in PREFIX_RESOURCES + SCALAR_RESOURCES
                or want not in (READ, WRITE)):
            return {"allowed": False}
        try:
            self.server.acl_check(body, kind, body.get("name", ""), want)
        except RPCError:
            return {"allowed": False}
        return {"allowed": True}

    async def node_info(self, body: dict):
        self.server.acl_check(body, "node", body.get("node", ""), READ)

        def run(ws):
            idx1, node = self.server.store.node(body["node"], ws=ws)
            idx2, svcs = self.server.store.node_services(body["node"], ws=ws)
            idx3, checks = self.server.store.node_checks(body["node"], ws=ws)
            return max(idx1, idx2, idx3), {
                "dump": [] if node is None else [
                    {"node": node, "services": svcs, "checks": checks}
                ]
            }

        return await self._read("Internal.NodeInfo", body, run)

    async def node_dump(self, body: dict):
        # internal_endpoint.go NodeDump is filtered per node
        # (filterACL); collapsed to a node read check on the whole dump.
        self.server.acl_check(body, "node", "", READ)

        def run(ws):
            idx, nodes = self.server.store.nodes(ws=ws)
            # Watch + index across ALL three tables, or a blocking dump
            # would sleep through service/check-only changes.
            idx = max(idx, self.server.store.max_index("services", "checks"))
            dump = []
            for n in nodes:
                _, svcs = self.server.store.node_services(n["node"], ws=ws)
                _, checks = self.server.store.node_checks(n["node"], ws=ws)
                dump.append({"node": n, "services": svcs, "checks": checks})
            if ws is not None:
                self.server.store.table_watch("services", ws)
                self.server.store.table_watch("checks", ws)
            return idx, {"dump": dump}

        return await self._read("Internal.NodeDump", body, run)


class Operator(_Endpoint):
    """operator_raft_endpoint.go / operator_autopilot_endpoint.go."""

    async def raft_get_configuration(self, body: dict):
        self.server.acl_check(body, "operator", "", READ)
        raft = self.server.raft
        servers = []
        if raft is not None:
            for vid in raft.voters:
                servers.append({
                    "id": vid,
                    "address": self.server._raft_peer_addr(vid) or "",
                    "leader": vid == raft.leader_id,
                    "voter": True,
                })
            for nid in raft.non_voters:
                servers.append({
                    "id": nid,
                    "address": self.server._raft_peer_addr(nid) or "",
                    "leader": False,
                    "voter": False,
                })
        return {"servers": servers, "index": raft.commit_index if raft else 0}

    async def autopilot_get_configuration(self, body: dict):
        """operator_autopilot_endpoint.go AutopilotGetConfiguration."""
        self.server.acl_check(body, "operator", "", READ)
        _, entry = self.server.store.config_entry_get(
            "autopilot-config", "global")
        cfg = self.server.config
        defaults = {
            "cleanup_dead_servers": cfg.autopilot_cleanup_dead_servers,
            "last_contact_threshold_s": cfg.autopilot_grace_s,
            "server_stabilization_time_s":
                cfg.autopilot_server_stabilization_s,
            "max_trailing_logs": cfg.autopilot_max_trailing_logs,
        }
        if entry:
            defaults.update({
                k: v for k, v in entry.items()
                if k in defaults
            })
            defaults["modify_index"] = entry.get("modify_index", 0)
        return {"config": defaults}

    async def autopilot_set_configuration(self, body: dict):
        """operator_autopilot_endpoint.go AutopilotSetConfiguration
        (CAS supported via ?cas=<modify_index>)."""
        self.server.acl_check(body, "operator", "", WRITE)
        fwd = await self.server.forward(
            "Operator.AutopilotSetConfiguration", body)
        if fwd is not None:
            return fwd
        result = await self.server.raft_apply(
            MessageType.AUTOPILOT,
            {"config": body.get("config") or {},
             "cas": bool(body.get("cas")),
             "modify_index": int(body.get("modify_index", 0) or 0)},
        )
        self.server.apply_autopilot_overrides()
        return {"result": result}

    async def raft_remove_peer_by_id(self, body: dict):
        self.server.acl_check(body, "operator", "", WRITE)
        fwd = await self.server.forward("Operator.RaftRemovePeerByID", body)
        if fwd is not None:
            return fwd
        if self.server.raft is None:
            return {"removed": False}
        await self.server.raft.remove_server(body["id"])
        return {"removed": True}

    async def server_health(self, body: dict):
        """operator_autopilot_endpoint.go ServerHealth — the autopilot
        health records (healthy flag, stable-since age, log index,
        voter) plus the cluster roll-up.  On a non-leader the log-lag
        component is unknown (match_index is leader state), so health
        there reflects serf liveness only."""
        srv = self.server
        members = srv._server_members()
        raft = srv.raft
        # Refresh the records on read so a non-leader (or a quiet
        # leader between autopilot passes) still answers accurately.
        srv._autopilot_update_health()
        now = time.monotonic()
        servers = []
        for m in members:
            rec = srv._server_health.get(m.tags.get("id"), {})
            servers.append({
                "id": m.tags.get("id", ""),
                "name": m.name,
                "serf_status": m.status.name.lower(),
                "healthy": bool(rec.get("healthy", False)),
                "stable_since_s": round(
                    now - rec["stable_since"], 3
                ) if rec.get("stable_since") else 0.0,
                "last_index": rec.get("last_index", 0),
                "voter": raft is not None
                and m.tags.get("id") in raft.voters,
            })
        healthy_voters = sum(
            1 for s in servers if s["healthy"] and s["voter"]
        )
        total_voters = len(raft.voters) if raft is not None else 0
        quorum = total_voters // 2 + 1 if total_voters else 0
        return {
            "healthy": all(s["healthy"] for s in servers) and bool(
                raft is not None and raft.leader_id is not None
            ),
            "servers": servers,
            # How many MORE healthy voters can fail before quorum is
            # lost — measured against the full voter set's quorum, so
            # already-failed voters count against it
            # (autopilot/structs.go OperatorHealthReply).
            "failure_tolerance": max(0, healthy_voters - quorum),
        }


def _wrap(idx_and_data: tuple[int, Any], key: str) -> tuple[int, dict]:
    idx, data = idx_and_data
    return idx, {key: data}


class ConnectCA(_Endpoint):
    """connect_ca_endpoint.go: roots + leaf signing.  The built-in CA
    lives on the leader; the active root record is replicated so every
    server serves Roots."""

    async def roots(self, body: dict):
        return await self._read(
            "ConnectCA.Roots", body,
            lambda ws: _wrap(self.server.store.ca_roots(ws=ws), "roots"),
        )

    async def sign(self, body: dict):
        """Sign a leaf for a service (connect_ca_endpoint.go Sign):
        leader-only (it holds the private key)."""
        self.server.acl_check(
            body, "service", body.get("service", ""), WRITE
        )
        fwd = await self.server.forward("ConnectCA.Sign", body)
        if fwd is not None:
            return fwd
        ca = await self.server.connect_ca()
        leaf = ca.sign_leaf(body["service"])
        return {"leaf": leaf}

    async def rotate(self, body: dict):
        """Mint + activate a new signing root (leader_connect.go CA
        config update path): the outgoing key CROSS-SIGNS the new root
        (provider_consul.go CrossSignCA) so old-root-pinned peers keep
        verifying new leaves via the chain; old roots stay stored so
        outstanding leaves verify until expiry, and proxies roll their
        certs when they observe the new active root."""
        self.server.acl_check(body, "operator", "", WRITE)
        fwd = await self.server.forward("ConnectCA.Rotate", body)
        if fwd is not None:
            return fwd
        ca = await self.server.connect_ca()
        root = ca.rotate()
        await self.server.raft_apply(
            MessageType.CONNECT_CA, {"op": "set-root", "root": root}
        )
        return {"root_id": root["id"]}


class Intention(_Endpoint):
    """intention_endpoint.go: CRUD + match + connect authorize."""

    async def apply(self, body: dict):
        intention = dict(body.get("intention") or {})
        self.server.acl_check(
            body, "service", intention.get("destination", ""), WRITE
        )
        if body.get("op") in ("create", "update"):
            if not intention.get("destination"):
                raise ValueError("intention requires a destination")
            intention.setdefault("source", "*")
            intention.setdefault("id", str(uuid.uuid4()))
            intention.setdefault("action", "allow")
            body = {**body, "intention": intention}
        if body.get("op") == "create":
            # One intention per (source, destination) pair — a second
            # create must not shadow the first in the precedence walk
            # (intention_endpoint.go Apply duplicate check).
            _, rows = self.server.store.intention_match(
                intention["destination"]
            )
            if any(r["source"] == intention["source"]
                   and r["destination"] == intention["destination"]
                   for r in rows):
                raise ValueError(
                    f"duplicate intention {intention['source']!r} -> "
                    f"{intention['destination']!r}")
        out = await self._write(
            "Intention.Apply", MessageType.INTENTION, body
        )
        out.setdefault("intention", intention)
        return out

    async def list(self, body: dict):
        return await self._read(
            "Intention.List", body,
            lambda ws: _wrap(self.server.store.intention_list(ws=ws),
                             "intentions"),
        )

    async def get(self, body: dict):
        def run(ws):
            idx, rec = self.server.store.intention_get(body["id"], ws=ws)
            return idx, {"intentions": [rec] if rec else []}

        return await self._read("Intention.Get", body, run)

    async def match(self, body: dict):
        self.server.acl_check(
            body, "service", body.get("destination", ""), READ
        )
        # default_allow rides along so enforcement points (proxies)
        # apply the same fallback as Intention.Check without a second
        # RPC (intention_endpoint.go Match + DefaultDecision).
        default_allow = (
            not self.server.acl.enabled
            or self.server.acl.default_policy == "allow"
        )

        def run(ws):
            idx, rows = self.server.store.intention_match(
                body.get("destination", ""), ws=ws
            )
            return idx, {"intentions": rows, "default_allow": default_allow}

        return await self._read("Intention.Match", body, run)

    async def check(self, body: dict):
        """Connect authorize core (intention_endpoint.go Check +
        consul/intention_endpoint.go Test): walk matching intentions by
        precedence; first source match decides; default follows the ACL
        default policy (intentions deny-by-default only when ACLs
        do)."""
        self.server.acl_check(
            body, "service", body.get("destination", ""), READ
        )
        source = body.get("source", "")
        _, matches = self.server.store.intention_match(
            body.get("destination", "")
        )
        for intention in matches:
            if intention["source"] in (source, "*"):
                return {
                    "allowed": intention.get("action", "allow") == "allow",
                    "reason": f"matched intention {intention['id']}",
                }
        default_allow = (
            not self.server.acl.enabled
            or self.server.acl.default_policy == "allow"
        )
        return {"allowed": default_allow, "reason": "default policy"}


class DiscoveryChain(_Endpoint):
    """discovery_chain_endpoint.go Get: compile one service's chain
    from the current config entries, blocking on entry changes."""

    async def get(self, body: dict):
        from consul_tpu.connect.discoverychain import (
            compile_chain,
            entries_for_chain,
        )

        name = body.get("name", "")
        self.server.acl_check(body, "service", name, READ)

        def run(ws):
            idx, entries = entries_for_chain(self.server.store, name, ws=ws)
            chain = compile_chain(
                name, self.server.config.datacenter, entries,
                use_in_datacenter=body.get("use_in_datacenter", ""),
                override_protocol=body.get("override_protocol", ""),
                override_connect_timeout_s=float(
                    body.get("override_connect_timeout_s", 0) or 0),
            )
            return max(idx, 1), {"chain": chain}

        return await self._read("DiscoveryChain.Get", body, run)


class AutoEncrypt(_Endpoint):
    """consul/auto_encrypt_endpoint.go: a CLIENT agent bootstraps its
    TLS identity — an agent-kind SPIFFE leaf + the CA roots — in one
    RPC at startup, before it can do anything else."""

    async def sign(self, body: dict):
        # auto_encrypt_endpoint.go Sign: an anonymous caller must not be
        # able to mint an agent identity for an arbitrary node — require
        # node:write on the claimed node name (the intro/agent token).
        self.server.acl_check(body, "node", body.get("node", ""), WRITE)
        fwd = await self.server.forward("AutoEncrypt.Sign", body)
        if fwd is not None:
            return fwd
        ca = await self.server.connect_ca()
        leaf = ca.sign_leaf(body.get("node", ""), kind="agent")
        _, roots = self.server.store.ca_roots()
        return {"leaf": leaf, "roots": roots}


def _interpolate_bind_name(template: str, vars_: dict[str, str]) -> str:
    """``${var}`` interpolation over projected identity vars
    (agent/consul/acl_endpoint.go computeBindingRuleBindName →
    lib.InterpolateHIL).  Unknown vars raise KeyError so a login can
    never silently bind to a half-substituted name."""
    import re as _re

    def sub(m):
        name = m.group(1)
        if name not in vars_:
            raise KeyError(name)
        return vars_[name]

    return _re.sub(r"\$\{([A-Za-z0-9_.]+)\}", sub, template)


class AutoConfig(_Endpoint):
    """consul/auto_config_endpoint.go InitialConfiguration: a brand-new
    CLIENT with nothing but a server address and a JWT intro token
    bootstraps its full runtime — gossip encryption keys, an ACL agent
    token, its TLS identity, and cluster-level settings — in ONE RPC,
    before it can join gossip or speak ACL'd RPCs."""

    async def initial_configuration(self, body: dict):
        fwd = await self.server.forward(
            "AutoConfig.InitialConfiguration", body
        )
        if fwd is not None:
            return fwd
        authz = self.server.config.auto_config_authorizer
        if not authz:
            raise RPCError("auto-config is disabled on this server")
        node = body.get("node", "")
        if not node:
            raise ValueError("auto-config request must name a node")
        # The node name is caller-controlled AND interpolated into the
        # claim-assertion selectors below — restrict it to the hostname
        # alphabet so it can never smuggle bexpr syntax
        # (auto_config_endpoint.go validates against InvalidDnsRe the
        # same way).
        import re as _re

        if not _re.fullmatch(r"[A-Za-z0-9_.-]{1,128}", node):
            raise ValueError(f"invalid node name {node!r}")
        from consul_tpu.acl import jwt as jwt_mod

        try:
            claims = jwt_mod.validate(
                body.get("jwt", ""),
                secret=authz.get("jwt_secret", ""),
                pub_keys=authz.get("jwt_validation_pub_keys") or [],
                bound_issuer=authz.get("bound_issuer", ""),
                bound_audiences=authz.get("bound_audiences") or [],
                clock_skew_s=float(authz.get("clock_skew_s", 30.0)),
            )
        except jwt_mod.JWTError as e:
            raise RPCError(ERR_PERMISSION_DENIED) from e
        selectable, _projected = jwt_mod.identity_from_claims(
            claims,
            authz.get("claim_mappings") or {},
            authz.get("list_claim_mappings") or {},
        )
        # auto_config_endpoint.go claim assertions: every configured
        # selector must match the verified identity; @@node@@ stands in
        # for the claimed node name (lib.InterpolateHIL equivalent).
        from consul_tpu.agent.bexpr import FilterError, create_filter

        for raw in authz.get("claim_assertions") or []:
            selector = raw.replace("${node}", node)
            try:
                if not create_filter(selector).match(selectable):
                    raise RPCError(ERR_PERMISSION_DENIED)
            except FilterError as e:
                raise RPCError(ERR_PERMISSION_DENIED) from e

        cfg = self.server.config
        out: dict = {
            "config": {
                "datacenter": cfg.datacenter,
                "primary_datacenter": cfg.primary_datacenter
                or cfg.datacenter,
                "node_name": node,
                "acl": {
                    "enabled": cfg.acl_enabled,
                    "default_policy": cfg.acl_default_policy,
                },
            },
            # Primary key FIRST, then the rest of the ring — a client
            # bootstrapping mid-rotation must decrypt traffic still
            # using older keys.
            "gossip_keys": (
                [cfg.keyring.primary_b64()]
                + [k for k in cfg.keyring.list_keys()
                   if k != cfg.keyring.primary_b64()]
                if cfg.keyring else []
            ),
        }
        if cfg.acl_enabled:
            # Mint (or REUSE) a node-identity agent token so
            # anti-entropy and agent-plane RPCs work under enforcement
            # (auto_config_endpoint.go updateTokenResponse persists and
            # reuses) — a retrying or restarting client must not grow
            # an orphaned token per call.
            desc = f"auto-config token for node {node!r}"
            _, tokens = self.server.store.acl_token_list()
            existing = next(
                (t for t in tokens
                 if t.get("description") == desc
                 and t.get("node_identities")), None,
            )
            if existing is not None:
                secret = existing["secret_id"]
            else:
                token = {
                    "secret_id": str(uuid.uuid4()),
                    "accessor_id": str(uuid.uuid4()),
                    "description": desc,
                    "auth_method": "",
                    "local": True,
                    "node_identities": [
                        {"node_name": node, "datacenter": cfg.datacenter}
                    ],
                }
                await self.server.raft_apply(
                    MessageType.ACL_TOKEN_SET, {"token": token}
                )
                secret = token["secret_id"]
            out["config"]["acl"]["tokens"] = {"agent": secret}
        # TLS identity, exactly the auto-encrypt shape.
        ca = await self.server.connect_ca()
        leaf = ca.sign_leaf(node, kind="agent")
        _, roots = self.server.store.ca_roots()
        out["tls"] = {"leaf": leaf, "roots": roots}
        return out


class ACL(_Endpoint):
    """acl_endpoint.go — token/policy CRUD + one-shot bootstrap.

    Bootstrap (acl_endpoint.go:56-118 BootstrapTokens): allowed only
    while no management token exists; returns a generated management
    secret.  All other methods require acl read/write via a resolved
    token (consul/acl_endpoint.go authorizers)."""

    def __init__(self, server):
        super().__init__(server)
        self._bootstrap_lock = asyncio.Lock()

    async def bootstrap(self, body: dict):
        fwd = await self.server.forward("ACL.Bootstrap", body)
        if fwd is not None:
            return fwd
        # Serialize check-then-apply so concurrent bootstraps can't both
        # mint a management token (acl_endpoint.go guards with the
        # bootstrap reset index through raft).
        async with self._bootstrap_lock:
            _, tokens = self.server.store.acl_token_list()
            if any(t.get("type") == "management" for t in tokens):
                raise ValueError("ACL bootstrap no longer allowed")
            secret = str(uuid.uuid4())
            token = {
                "secret_id": secret,
                "description": "Bootstrap Token (Global Management)",
                "type": "management",
                "policies": [],
            }
            await self.server.raft_apply(
                MessageType.ACL_TOKEN_SET, {"token": token}
            )
        self.server.acl.invalidate()
        return {"token": token}

    async def token_set(self, body: dict):
        # Forward the ORIGINAL body (auth token intact) and transform on
        # the executing leader only — a follower must never forward a
        # half-built raft payload back into this endpoint.
        self.server.acl_check(body, "acl", "", WRITE)
        fwd = await self.server.forward("ACL.TokenSet", body)
        if fwd is not None:
            return fwd
        token = dict(body.get("acl_token") or body.get("new_token") or {})
        token.setdefault("secret_id", str(uuid.uuid4()))
        # acl_endpoint.go:456-481: a relative TTL is converted into an
        # absolute expiration at create time and never stored itself.
        ttl = float(token.pop("expiration_ttl_s", 0) or 0)
        if ttl < 0:
            raise ValueError("Token Expiration TTL should be > 0")
        if ttl:
            if token.get("expiration_time"):
                raise ValueError(
                    "Token cannot have both an ExpirationTTL "
                    "and an ExpirationTime"
                )
            token["expiration_time"] = time.time() + ttl
        for rid in token.get("roles", []):
            if self.server.store.acl_role_get(rid) is None:
                raise ValueError(f"no such ACL role {rid!r}")
        result = await self.server.raft_apply(
            MessageType.ACL_TOKEN_SET, {"token": token}
        )
        self.server.acl.invalidate(token["secret_id"])
        return {"result": result, "token": token}

    async def token_delete(self, body: dict):
        self.server.acl_check(body, "acl", "", WRITE)
        fwd = await self.server.forward("ACL.TokenDelete", body)
        if fwd is not None:
            return fwd
        result = await self.server.raft_apply(
            MessageType.ACL_TOKEN_DELETE, {"secret_id": body["secret_id"]}
        )
        self.server.acl.invalidate(body["secret_id"])
        return {"result": result}

    async def token_list(self, body: dict):
        self.server.acl_check(body, "acl", "", READ)
        idx, tokens = self.server.store.acl_token_list()
        # Secrets are redacted for mere acl:read (the reference exposes
        # them only to acl:write).
        if not self.server.acl_resolve(body).acl_write():
            tokens = [
                {**t, "secret_id": "<hidden>"} for t in tokens
            ]
        return {"tokens": tokens, "meta": {"index": idx}}

    async def token_read(self, body: dict):
        self.server.acl_check(body, "acl", "", READ)
        rec = self.server.store.acl_token_get(body["secret_id"])
        return {"token": rec}

    async def policy_set(self, body: dict):
        self.server.acl_check(body, "acl", "", WRITE)
        fwd = await self.server.forward("ACL.PolicySet", body)
        if fwd is not None:
            return fwd
        policy = dict(body.get("policy") or {})
        policy.setdefault("id", str(uuid.uuid4()))
        result = await self.server.raft_apply(
            MessageType.ACL_POLICY_SET, {"policy": policy}
        )
        self.server.acl.invalidate()
        return {"result": result, "policy": policy}

    async def policy_delete(self, body: dict):
        self.server.acl_check(body, "acl", "", WRITE)
        fwd = await self.server.forward("ACL.PolicyDelete", body)
        if fwd is not None:
            return fwd
        result = await self.server.raft_apply(
            MessageType.ACL_POLICY_DELETE, {"id": body["id"]}
        )
        self.server.acl.invalidate()
        return {"result": result}

    async def policy_list(self, body: dict):
        self.server.acl_check(body, "acl", "", READ)
        idx, policies = self.server.store.acl_policy_list()
        return {"policies": policies, "meta": {"index": idx}}

    async def policy_read(self, body: dict):
        self.server.acl_check(body, "acl", "", READ)
        rec = self.server.store.acl_policy_get(body["id"])
        return {"policy": rec}

    # -- roles (acl_endpoint.go RoleSet/RoleDelete/RoleList/RoleRead) ------

    async def role_set(self, body: dict):
        self.server.acl_check(body, "acl", "", WRITE)
        fwd = await self.server.forward("ACL.RoleSet", body)
        if fwd is not None:
            return fwd
        role = dict(body.get("role") or {})
        if not role.get("name"):
            raise ValueError("ACL role must have a name")
        role.setdefault("id", str(uuid.uuid4()))
        existing = self.server.store.acl_role_get_by_name(role["name"])
        if existing is not None and existing["id"] != role["id"]:
            raise ValueError(
                f"role name {role['name']!r} is already in use"
            )
        for pid in role.get("policies", []):
            if self.server.store.acl_policy_get(pid) is None:
                raise ValueError(f"no such ACL policy {pid!r}")
        result = await self.server.raft_apply(
            MessageType.ACL_ROLE_SET, {"role": role}
        )
        self.server.acl.invalidate()
        return {"result": result, "role": role}

    async def role_delete(self, body: dict):
        self.server.acl_check(body, "acl", "", WRITE)
        fwd = await self.server.forward("ACL.RoleDelete", body)
        if fwd is not None:
            return fwd
        result = await self.server.raft_apply(
            MessageType.ACL_ROLE_DELETE, {"id": body["id"]}
        )
        self.server.acl.invalidate()
        return {"result": result}

    async def role_list(self, body: dict):
        self.server.acl_check(body, "acl", "", READ)
        idx, roles = self.server.store.acl_role_list()
        return {"roles": roles, "meta": {"index": idx}}

    async def role_read(self, body: dict):
        self.server.acl_check(body, "acl", "", READ)
        if body.get("name"):
            rec = self.server.store.acl_role_get_by_name(body["name"])
        else:
            rec = self.server.store.acl_role_get(body["id"])
        return {"role": rec}

    # -- auth methods (acl_endpoint.go AuthMethodSet/...) ------------------

    async def auth_method_set(self, body: dict):
        self.server.acl_check(body, "acl", "", WRITE)
        fwd = await self.server.forward("ACL.AuthMethodSet", body)
        if fwd is not None:
            return fwd
        method = dict(body.get("auth_method") or {})
        if not method.get("name"):
            raise ValueError("auth method must have a name")
        if method.get("type") not in ("jwt",):
            raise ValueError(
                f"invalid auth method type {method.get('type')!r} "
                "(supported: jwt)"
            )
        ttl = float(method.get("max_token_ttl_s", 0) or 0)
        if ttl < 0:
            raise ValueError("max_token_ttl_s should be >= 0")
        result = await self.server.raft_apply(
            MessageType.ACL_AUTH_METHOD_SET, {"method": method}
        )
        return {"result": result, "auth_method": method}

    async def auth_method_delete(self, body: dict):
        self.server.acl_check(body, "acl", "", WRITE)
        fwd = await self.server.forward("ACL.AuthMethodDelete", body)
        if fwd is not None:
            return fwd
        result = await self.server.raft_apply(
            MessageType.ACL_AUTH_METHOD_DELETE, {"name": body["name"]}
        )
        # The cascade may have deleted tokens — drop all cached authz.
        self.server.acl.invalidate()
        return {"result": result}

    async def auth_method_list(self, body: dict):
        self.server.acl_check(body, "acl", "", READ)
        idx, methods = self.server.store.acl_auth_method_list()
        return {"auth_methods": methods, "meta": {"index": idx}}

    async def auth_method_read(self, body: dict):
        self.server.acl_check(body, "acl", "", READ)
        rec = self.server.store.acl_auth_method_get(body["name"])
        return {"auth_method": rec}

    # -- binding rules (acl_endpoint.go BindingRuleSet/...) ----------------

    async def binding_rule_set(self, body: dict):
        self.server.acl_check(body, "acl", "", WRITE)
        fwd = await self.server.forward("ACL.BindingRuleSet", body)
        if fwd is not None:
            return fwd
        rule = dict(body.get("binding_rule") or {})
        if not rule.get("auth_method"):
            raise ValueError("binding rule must name an auth method")
        method = self.server.store.acl_auth_method_get(rule["auth_method"])
        if method is None:
            raise ValueError(
                f"no such auth method {rule['auth_method']!r}"
            )
        if rule.get("bind_type") not in ("role", "service", "node"):
            raise ValueError(
                f"invalid bind_type {rule.get('bind_type')!r} "
                "(role|service|node)"
            )
        if not rule.get("bind_name"):
            raise ValueError("binding rule must have a bind_name")
        # Vet the template against the method's projected vars NOW
        # (acl_endpoint.go BindingRuleSet → validateBindingRuleBindName
        # with validator.ProjectedVarNames) — a typo'd ${var} must fail
        # the write, not every later login.
        cfg = method.get("config") or {}
        known = {str(v) for v in (cfg.get("claim_mappings") or {}).values()}
        try:
            _interpolate_bind_name(
                rule["bind_name"], dict.fromkeys(known, "x")
            )
        except KeyError as e:
            raise ValueError(
                f"bind_name references unknown variable {e} "
                f"(auth method maps: {sorted(known) or 'none'})"
            ) from e
        if rule.get("selector"):
            from consul_tpu.agent.bexpr import create_filter
            create_filter(rule["selector"])  # syntax check up front
        rule.setdefault("id", str(uuid.uuid4()))
        result = await self.server.raft_apply(
            MessageType.ACL_BINDING_RULE_SET, {"rule": rule}
        )
        return {"result": result, "binding_rule": rule}

    async def binding_rule_delete(self, body: dict):
        self.server.acl_check(body, "acl", "", WRITE)
        fwd = await self.server.forward("ACL.BindingRuleDelete", body)
        if fwd is not None:
            return fwd
        result = await self.server.raft_apply(
            MessageType.ACL_BINDING_RULE_DELETE, {"id": body["id"]}
        )
        return {"result": result}

    async def binding_rule_list(self, body: dict):
        self.server.acl_check(body, "acl", "", READ)
        idx, rules = self.server.store.acl_binding_rule_list(
            body.get("auth_method", "")
        )
        return {"binding_rules": rules, "meta": {"index": idx}}

    async def binding_rule_read(self, body: dict):
        self.server.acl_check(body, "acl", "", READ)
        rec = self.server.store.acl_binding_rule_get(body["id"])
        return {"binding_rule": rec}

    # -- login / logout (acl_endpoint.go Login/Logout) ---------------------

    async def login(self, body: dict):
        """Exchange a bearer JWT for a Consul token
        (acl_endpoint.go:~Login → acl_authmethod.go
        evaluateRoleBindings).  Requires NO pre-existing token."""
        fwd = await self.server.forward("ACL.Login", body)
        if fwd is not None:
            return fwd
        auth = body.get("auth") or {}
        method_name = auth.get("auth_method", "")
        bearer = auth.get("bearer_token", "")
        method = self.server.store.acl_auth_method_get(method_name)
        if method is None:
            raise ValueError(f"no such auth method {method_name!r}")
        from consul_tpu.acl import jwt as jwt_mod

        cfg = method.get("config") or {}
        try:
            claims = jwt_mod.validate(
                bearer,
                secret=cfg.get("jwt_secret", ""),
                pub_keys=cfg.get("jwt_validation_pub_keys") or [],
                bound_issuer=cfg.get("bound_issuer", ""),
                bound_audiences=cfg.get("bound_audiences") or [],
                clock_skew_s=float(cfg.get("clock_skew_s", 30.0)),
            )
        except jwt_mod.JWTError as e:
            # Surfaced as the canonical 403 string; the detail stays in
            # the server log only (acl_endpoint.go wraps in
            # ErrPermissionDenied the same way).
            raise RPCError(ERR_PERMISSION_DENIED) from e
        selectable, projected = jwt_mod.identity_from_claims(
            claims,
            cfg.get("claim_mappings") or {},
            cfg.get("list_claim_mappings") or {},
        )
        bindings = self._evaluate_role_bindings(
            method_name, selectable, projected
        )
        if not any(bindings.values()):
            # acl_endpoint.go Login: no rule matched → no token.
            raise RPCError(ERR_PERMISSION_DENIED)
        ttl = float(method.get("max_token_ttl_s", 0) or 0)
        token = {
            "secret_id": str(uuid.uuid4()),
            "accessor_id": str(uuid.uuid4()),
            "description": (
                f"token created via login: {auth.get('meta') or {}}"
            ),
            "auth_method": method_name,
            "local": True,
            "roles": bindings["roles"],
            "service_identities": bindings["service_identities"],
            "node_identities": bindings["node_identities"],
        }
        if ttl:
            token["expiration_time"] = time.time() + ttl
        await self.server.raft_apply(
            MessageType.ACL_TOKEN_SET, {"token": token}
        )
        return {"token": token}

    def _evaluate_role_bindings(
        self, method_name: str, selectable: dict, projected: dict
    ) -> dict:
        """acl_authmethod.go evaluateRoleBindings: match selectors
        against the verified identity, then interpolate bind names."""
        from consul_tpu.agent.bexpr import FilterError, create_filter

        _, rules = self.server.store.acl_binding_rule_list(method_name)
        out = {"roles": [], "service_identities": [], "node_identities": []}
        for rule in rules:
            selector = rule.get("selector", "")
            if selector:
                try:
                    if not create_filter(selector).match(selectable):
                        continue
                except FilterError:
                    continue  # invalid selector fails closed
            try:
                bind_name = _interpolate_bind_name(
                    rule["bind_name"], projected
                )
            except KeyError:
                # The JWT simply lacks a mapped claim this rule needs —
                # skip the rule (no privileges granted) rather than
                # failing the whole login alongside rules that matched.
                continue
            if rule["bind_type"] == "service":
                out["service_identities"].append(
                    {"service_name": bind_name}
                )
            elif rule["bind_type"] == "node":
                out["node_identities"].append({
                    "node_name": bind_name,
                    "datacenter": self.server.config.datacenter,
                })
            elif rule["bind_type"] == "role":
                role = self.server.store.acl_role_get_by_name(bind_name)
                if role is not None:
                    out["roles"].append(role["id"])
        return out

    async def logout(self, body: dict):
        """Destroy the requesting token itself; only tokens minted by an
        auth method may log out (acl_endpoint.go Logout)."""
        fwd = await self.server.forward("ACL.Logout", body)
        if fwd is not None:
            return fwd
        secret = body.get("token", "")
        rec = self.server.store.acl_token_get(secret)
        if rec is None or not rec.get("auth_method"):
            raise RPCError(ERR_PERMISSION_DENIED)
        result = await self.server.raft_apply(
            MessageType.ACL_TOKEN_DELETE, {"secret_id": secret}
        )
        self.server.acl.invalidate(secret)
        return {"result": result}


class FederationState(_Endpoint):
    """federation_state_endpoint.go — CRUD over the per-DC mesh-gateway
    map.  Writes ALWAYS land in the primary datacenter and replicate
    outward (federation_state_endpoint.go:25-28)."""

    async def apply(self, body: dict):
        # Rewrite the target DC to the primary BEFORE forwarding — every
        # federation-state write goes to the primary's raft.
        body = {**body, "dc": self.server.config.primary_datacenter
                or self.server.config.datacenter}
        fwd = await self.server.forward("FederationState.Apply", body)
        if fwd is not None:
            return fwd
        self.server.acl_check(body, "operator", "", WRITE)
        state = body.get("state") or {}
        if not state.get("datacenter"):
            raise ValueError(
                "invalid request: missing federation state datacenter"
            )
        op = body.get("op", "upsert")
        if op not in ("upsert", "delete"):
            raise ValueError(f"Invalid federation state operation: {op}")
        result = await self.server.raft_apply(
            MessageType.FEDERATION_STATE, {"op": op, "state": state}
        )
        return {"result": result}

    async def get(self, body: dict):
        self.server.acl_check(body, "operator", "", READ)

        def run(ws):
            idx, state = self.server.store.federation_state_get(
                body["target_dc"], ws=ws
            )
            return max(idx, 1), {"state": state}

        return await self._read("FederationState.Get", body, run)

    async def list(self, body: dict):
        self.server.acl_check(body, "operator", "", READ)

        def run(ws):
            idx, states = self.server.store.federation_state_list(ws=ws)
            return max(idx, 1), {"states": states}

        return await self._read("FederationState.List", body, run)

    async def list_mesh_gateways(self, body: dict):
        """DC → healthy-ish mesh gateway instances, the data plane's
        cross-DC routing table (federation_state_endpoint.go
        ListMeshGateways).  Gateways are services — service:read
        filtering applies like any catalog read."""

        def run(ws):
            idx, states = self.server.store.federation_state_list(ws=ws)
            authz = self._authz(body)
            out = {}
            for st in states:
                gws = st.get("mesh_gateways", [])
                if authz is not None:
                    gws = [g for g in gws
                           if authz.service_read(g.get("service", ""))]
                if gws:
                    out[st["datacenter"]] = gws
            return max(idx, 1), {"gateways": out}

        return await self._read(
            "FederationState.ListMeshGateways", body, run
        )


class Snapshot(_Endpoint):
    """snapshot_endpoint.go: atomic save/restore of the full state.
    The reference gates both on management tokens; approximated here as
    operator read (save) / operator write (restore)."""

    async def save(self, body: dict):
        from consul_tpu.agent.snapshot import write_archive

        self.server.acl_check(body, "operator", "", READ)
        fwd = await self.server.forward("Snapshot.Save", body)
        if fwd is not None:
            return fwd
        # Saved from the leader for a consistent, current view
        # (snapshot_endpoint.go defaults to consistent mode).
        raft = self.server.raft
        index = raft.last_index() if raft else 0
        term = raft.last_term() if raft else 0
        blob = write_archive(
            self.server.fsm.snapshot(), index, term, self.server.node_id
        )
        return {"archive": blob, "index": index}

    async def restore(self, body: dict):
        from consul_tpu.agent.snapshot import SnapshotError, read_archive

        self.server.acl_check(body, "operator", "", WRITE)
        fwd = await self.server.forward("Snapshot.Restore", body)
        if fwd is not None:
            return fwd
        try:
            state, meta = read_archive(body["archive"])
        except SnapshotError as e:
            raise ValueError(str(e)) from e
        await self.server.raft_apply(
            MessageType.SNAPSHOT_RESTORE, {"state": state}
        )
        return {"result": True, "meta": meta}


class Subscribe(_Endpoint):
    """agent/rpc/subscribe/subscribe.go:45 — server-streaming change
    subscriptions: a snapshot of current state (closed by an
    end_of_snapshot marker), then live events as commits land.  Rides
    the muxed RPC port as a streaming method instead of gRPC."""

    async def subscribe(self, body: dict):
        from consul_tpu.stream import SubscriptionClosed

        topic = body["topic"]
        key = body.get("key", "")
        # subscribe.go filterByAuth: resolve the subscriber's token up
        # front and drop events its authorizer cannot read.  Re-resolve
        # per event so token invalidation takes effect mid-stream.
        def readable(ev) -> bool:
            if not self.server.acl.enabled:
                return True
            authz = self.server.acl_resolve(body)
            if ev.end_of_snapshot:
                return True
            if ev.topic == "kv":
                return authz.key_read(ev.key)
            return authz.service_read(ev.key)

        sub = self.server.publisher.subscribe(topic, key)
        try:
            while True:
                ev = await sub.next()
                if not readable(ev):
                    continue
                yield {
                    "topic": ev.topic,
                    "key": ev.key,
                    "index": ev.index,
                    "payload": ev.payload,
                    "end_of_snapshot": ev.end_of_snapshot,
                }
        except SubscriptionClosed:
            # Store was rebuilt (snapshot restore): tell the consumer to
            # resubscribe for a fresh snapshot (pbsubscribe
            # NewSnapshotToFollow semantics, inverted: we end the
            # stream with a reset marker).
            yield {"reset": True}
        finally:
            sub.close()


def build_endpoints(server: "Server") -> dict[str, _Endpoint]:
    """The registry (server_oss.go:8-23)."""
    return {
        "Status": Status(server),
        "Catalog": Catalog(server),
        "Health": Health(server),
        "KVS": KVS(server),
        "Session": Session(server),
        "Coordinate": Coordinate(server),
        "Txn": Txn(server),
        "ConfigEntry": ConfigEntry(server),
        "PreparedQuery": PreparedQuery(server),
        "Internal": Internal(server),
        "Operator": Operator(server),
        "ACL": ACL(server),
        "AutoEncrypt": AutoEncrypt(server),
        "ConnectCA": ConnectCA(server),
        "Intention": Intention(server),
        "Snapshot": Snapshot(server),
        "Subscribe": Subscribe(server),
        "DiscoveryChain": DiscoveryChain(server),
        "FederationState": FederationState(server),
        "AutoConfig": AutoConfig(server),
    }
