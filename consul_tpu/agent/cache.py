"""Agent-side cache with background blocking-query refresh.

Equivalent of ``agent/cache`` + ``agent/cache-types`` (SURVEY.md §2.3):
a generic cache keyed by (type, request-key) where each *type* declares
how to fetch (an RPC method) and whether the entry supports background
refresh.  Semantics kept from the reference:

  Get              cache.go:285 — hit returns immediately; miss blocks
                   on a single-flight fetch (concurrent Gets for the
                   same key share one RPC)
  fetch            cache.go:488 — runs the RPC; for refresh types the
                   request carries min_query_index so the server
                   long-polls and returns only on change
  background       cache.go:717 — refresh types keep fetching in a
  refresh          loop after the first Get, so subsequent reads are
                   always warm and watchers learn of changes without
                   polling; errors back off (RefreshBackoffMin)
  TTL              entries unused for ``ttl`` seconds are evicted and
                   their refresh loop stopped (cache.go expiry heap)
  Notify           watch.go — register an asyncio.Queue to receive
                   every update of an entry

Registered types mirror ``cache-types/``: health services, catalog
services/nodes/node-services, KV gets, prepared-query execution (the
latter TTL-only, like the reference's prepared_simple type).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import Any, Awaitable, Callable, Optional

log = logging.getLogger("consul_tpu.cache")

# cache-types/*.go registration names (reference spelling).
HEALTH_SERVICES = "health-services"
CATALOG_SERVICES = "catalog-services"
CATALOG_LIST_NODES = "catalog-list-nodes"
CATALOG_NODE_SERVICES = "catalog-node-services"
KV_GET = "kv-get"
NODE_INFO = "internal-node-info"
PREPARED_QUERY = "prepared-query"
CONNECT_CA_ROOTS = "connect-ca-roots"
INTENTION_MATCH = "intention-match"
DISCOVERY_CHAIN = "discovery-chain"
FEDERATION_MESH_GATEWAYS = "federation-state-list-mesh-gateways"
SERVICE_KIND_NODES = "catalog-service-kind-nodes"
CATALOG_SERVICES_DUMP = "catalog-service-dump"

REFRESH_BACKOFF_MIN = 0.5   # cache.go RefreshBackoffMin (scaled-friendly)
REFRESH_TIMEOUT = 600.0     # cache-types' 10-minute blocking wait
MAX_REFRESH_TASKS = 512     # cap on concurrent background refreshers


@dataclasses.dataclass(frozen=True)
class CacheType:
    """One registered cache type (cache.go RegisterType)."""

    name: str
    method: str                       # RPC method to fetch with
    refresh: bool = True              # background blocking refresh?
    ttl: float = 600.0                # eviction after this much disuse
    key_fields: tuple = ()            # request fields forming the key


TYPES: dict[str, CacheType] = {
    t.name: t
    for t in (
        CacheType(HEALTH_SERVICES, "Health.ServiceNodes",
                  key_fields=("service", "tag", "passing_only", "connect",
                              "dc")),
        # proxycfg data sources (cache-types/connect_ca_root.go,
        # intention_match.go, discovery_chain.go).
        CacheType(CONNECT_CA_ROOTS, "ConnectCA.Roots", key_fields=("dc",)),
        CacheType(INTENTION_MATCH, "Intention.Match",
                  key_fields=("destination", "dc")),
        CacheType(DISCOVERY_CHAIN, "DiscoveryChain.Get",
                  key_fields=("name", "dc")),
        # cache-types/federation_state_list_mesh_gateways.go: the data
        # plane's cross-DC gateway map, blocking on federation states.
        CacheType(FEDERATION_MESH_GATEWAYS,
                  "FederationState.ListMeshGateways", key_fields=("dc",)),
        # Kind-indexed catalog watch (the reference's internal
        # ServiceDump kind filter) — local mesh-gateway discovery.
        CacheType(SERVICE_KIND_NODES, "Catalog.ServiceKindNodes",
                  key_fields=("kind", "passing_only", "dc")),
        CacheType(CATALOG_SERVICES_DUMP, "Catalog.ServiceDump",
                  key_fields=("dc",)),
        CacheType(CATALOG_SERVICES, "Catalog.ServiceNodes",
                  key_fields=("service", "tag", "dc")),
        CacheType(CATALOG_LIST_NODES, "Catalog.ListNodes",
                  key_fields=("dc",)),
        CacheType(CATALOG_NODE_SERVICES, "Catalog.NodeServices",
                  key_fields=("node", "dc")),
        CacheType(KV_GET, "KVS.Get", key_fields=("key", "dc")),
        CacheType(NODE_INFO, "Internal.NodeInfo", key_fields=("node", "dc")),
        # Prepared queries change rarely but executions are per-request;
        # the reference caches them TTL-only (no blocking refresh).
        CacheType(PREPARED_QUERY, "PreparedQuery.Execute", refresh=False,
                  ttl=3.0, key_fields=("query_id", "limit", "dc")),
    )
}


class _Entry:
    __slots__ = (
        "value", "meta", "index", "valid", "fetching", "waiters",
        "last_access", "fetched_at", "refresh_task", "watchers", "error",
    )

    def __init__(self) -> None:
        self.value: Any = None
        self.meta: dict = {}
        self.index = 0
        self.valid = False
        self.fetching = False
        self.waiters: list[asyncio.Future] = []
        self.last_access = time.monotonic()
        self.fetched_at = 0.0
        self.refresh_task: Optional[asyncio.Task] = None
        self.watchers: list[asyncio.Queue] = []
        self.error: Optional[Exception] = None


class AgentCache:
    """cache.go Cache."""

    def __init__(
        self,
        rpc: Callable[[str, dict], Awaitable[Any]],
        types: Optional[dict[str, CacheType]] = None,
        refresh_timeout: float = REFRESH_TIMEOUT,
        backoff_min: float = REFRESH_BACKOFF_MIN,
    ):
        self._rpc = rpc
        self._types = types or TYPES
        self._entries: dict[tuple, _Entry] = {}
        self._refresh_timeout = refresh_timeout
        self._backoff_min = backoff_min
        self.hits = 0
        self.misses = 0
        self._shutdown = False

    # ------------------------------------------------------------------

    def _key(self, t: CacheType, body: dict) -> tuple:
        return (t.name,) + tuple(
            repr(body.get(f)) for f in t.key_fields
        )

    async def get(self, type_name: str, body: dict) -> dict:
        """cache.go:285 Get: returns the RPC response body (with its
        meta) from cache, fetching on miss."""
        t = self._types[type_name]
        key = self._key(t, body)
        entry = self._entries.get(key)
        if entry is None:
            entry = _Entry()
            self._entries[key] = entry
        now = time.monotonic()
        entry.last_access = now
        # Refresh types stay valid as long as their background loop
        # lives; TTL-only types (prepared queries) age out and re-fetch
        # (cache.go:285 checks the expiry on hit for non-refresh types).
        fresh = entry.valid and (
            t.refresh or now - entry.fetched_at < t.ttl
        )
        if fresh:
            self.hits += 1
            return entry.value
        self.misses += 1
        self._maybe_sweep()
        await self._fetch(t, key, entry, dict(body))
        if entry.error is not None and not entry.valid:
            raise entry.error
        return entry.value

    def _maybe_sweep(self) -> None:
        """Drop expired TTL-only entries so distinct one-shot keys
        (e.g. prepared-query ids) can't accumulate without bound."""
        if len(self._entries) < 256:
            return
        now = time.monotonic()
        for key in list(self._entries):
            t = self._types.get(key[0])
            entry = self._entries[key]
            if (
                t is not None
                and not t.refresh
                and now - entry.last_access > t.ttl
            ):
                del self._entries[key]

    def notify(self, type_name: str, body: dict, q: asyncio.Queue) -> None:
        """watch.go Notify: q receives every subsequent update of the
        entry (requires a refresh type).  Call get() first to prime."""
        t = self._types[type_name]
        key = self._key(t, body)
        entry = self._entries.get(key)
        if entry is None:
            entry = _Entry()
            self._entries[key] = entry
        entry.watchers.append(q)

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stop(self) -> None:
        self._shutdown = True
        for entry in self._entries.values():
            if entry.refresh_task is not None:
                entry.refresh_task.cancel()
        self._entries.clear()

    # ------------------------------------------------------------------

    async def _fetch(self, t: CacheType, key: tuple, entry: _Entry,
                     body: dict) -> None:
        """Single-flight fetch (cache.go:488): concurrent callers await
        one in-flight RPC."""
        if entry.fetching:
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            entry.waiters.append(fut)
            await fut
            return
        entry.fetching = True
        try:
            result = await self._rpc(t.method, body)
            entry.value = result
            entry.meta = (result or {}).get("meta") or {}
            entry.index = int(entry.meta.get("index", 0) or 0)
            entry.valid = True
            entry.fetched_at = time.monotonic()
            entry.error = None
            self._notify_watchers(entry)
        except Exception as e:  # noqa: BLE001 - surfaced to the caller
            entry.error = e
        finally:
            entry.fetching = False
            for fut in entry.waiters:
                if not fut.done():
                    fut.set_result(None)
            entry.waiters.clear()
        if t.refresh and entry.refresh_task is None and not self._shutdown:
            # Cap background refreshers: a flood of distinct (possibly
            # bogus) names must not pin an unbounded task per key —
            # entries over the cap behave as TTL-only.
            active = sum(
                1 for e in self._entries.values()
                if e.refresh_task is not None and not e.refresh_task.done()
            )
            if active < MAX_REFRESH_TASKS:
                entry.refresh_task = asyncio.create_task(
                    self._refresh_loop(t, key, entry, body)
                )

    async def _refresh_loop(self, t: CacheType, key: tuple, entry: _Entry,
                            body: dict) -> None:
        """cache.go:717 refresh: blocking query against the last index;
        each change updates the entry in place and notifies watchers.
        Stops when the entry ages out (TTL disuse eviction)."""
        backoff = self._backoff_min
        while not self._shutdown:
            if time.monotonic() - entry.last_access > t.ttl:
                # Expired from disuse: drop the entry (expiry heap).
                if self._entries.get(key) is entry:
                    del self._entries[key]
                entry.refresh_task = None
                return
            req = dict(body)
            # A zero index would make the server answer immediately
            # (blocking_query only blocks for min_query_index > 0) and
            # this loop would hot-spin; ask from at least 1.
            req["min_query_index"] = max(entry.index, 1)
            req["max_query_time"] = self._refresh_timeout
            req["allow_stale"] = True
            try:
                result = await self._rpc(t.method, req)
                entry.value = result
                entry.meta = (result or {}).get("meta") or {}
                new_index = int(entry.meta.get("index", 0) or 0)
                changed = new_index != entry.index
                entry.index = new_index
                entry.valid = True
                entry.fetched_at = time.monotonic()
                backoff = self._backoff_min
                if changed:
                    self._notify_watchers(entry)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - transient RPC failures
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 30.0)

    def _notify_watchers(self, entry: _Entry) -> None:
        for q in entry.watchers:
            q.put_nowait(entry.value)
