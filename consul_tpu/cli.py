"""The command-line interface: ``python -m consul_tpu.cli <command>``.

Equivalent of the reference's ``command/`` registry
(``command/registry.go:16``, ~60 subcommands on top of the ``api/``
client).  Implemented commands: agent, members, join, leave,
force-leave, kv (get/put/delete/export/import), catalog
(datacenters/nodes/services), event, watch, exec-lock (lock), session
(list/destroy), info, rtt, operator raft list-peers, services
(register/deregister), monitor, version.

Every command except ``agent`` talks to a running agent over HTTP
(``-http-addr``, default 127.0.0.1:8500), exactly like the reference.
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import json
import math
import signal
import sys
from pathlib import Path
from typing import Optional

from consul_tpu.api import ConsulClient, parse_watch
from consul_tpu.version import __version__

DEFAULT_HTTP = "127.0.0.1:8500"

# Streamcast chunk-selection policies for `cli sim --policy`.  A
# LITERAL twin of consul_tpu.streamcast.model.POLICIES — the parser
# must build without importing the JAX-heavy sim tree — pinned equal
# in tests/test_streamcast.py so the copies cannot drift.
SIM_POLICY_CHOICES = ("uniform", "pipeline", "rarest")


def main(argv: Optional[list[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not hasattr(args, "fn"):
        parser.print_help()
        return 1
    try:
        return asyncio.run(args.fn(args)) or 0
    except KeyboardInterrupt:
        return 130
    except Exception as e:  # noqa: BLE001 — CLI surface: print, nonzero
        print(f"Error: {e}", file=sys.stderr)
        return 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="consul-tpu")
    sub = p.add_subparsers(dest="command")

    def cmd(name, fn, help=""):
        sp = sub.add_parser(name, help=help)
        sp.set_defaults(fn=fn)
        sp.add_argument("-http-addr", default=DEFAULT_HTTP)
        sp.add_argument("-token", default="",
                        help="ACL token (or X-Consul-Token equivalent)")
        return sp

    # agent ---------------------------------------------------------------
    sp = sub.add_parser("agent", help="run an agent")
    sp.set_defaults(fn=cmd_agent)
    sp.add_argument("-dev", action="store_true",
                    help="single-server dev mode")
    sp.add_argument("-server", action="store_true")
    sp.add_argument("-node", default="")
    sp.add_argument("-datacenter", default=None)
    sp.add_argument("-bootstrap-expect", type=int, default=None)
    sp.add_argument("-join", action="append", default=[])
    sp.add_argument("-bind", default=None)
    sp.add_argument("-serf-port", type=int, default=0)
    sp.add_argument("-rpc-port", type=int, default=0)
    sp.add_argument("-http-port", type=int, default=None)
    sp.add_argument("-dns-port", type=int, default=None)
    sp.add_argument("-config-file", action="append", default=[],
                    dest="config_file", help="JSON/HCL config file")
    sp.add_argument("-config-dir", action="append", default=[],
                    dest="config_dir")
    sp.add_argument("-data-dir", default=None, dest="data_dir",
                    help="persistence root (serf snapshot, rejoin state)")

    # cluster membership --------------------------------------------------
    cmd("members", cmd_members, "list gossip pool members")
    sp = cmd("join", cmd_join, "join an agent to a cluster")
    sp.add_argument("addresses", nargs="+")
    cmd("leave", cmd_leave, "gracefully leave the cluster")
    sp = cmd("force-leave", cmd_force_leave,
             "force a failed member into the left state")
    sp.add_argument("node")
    cmd("info", cmd_info, "agent runtime info")

    # kv -------------------------------------------------------------------
    sp = cmd("kv", cmd_kv, "key/value store ops")
    sp.add_argument("verb", choices=["get", "put", "delete", "export",
                                     "import"])
    sp.add_argument("key", nargs="?", default="")
    sp.add_argument("value", nargs="?", default=None)
    sp.add_argument("-recurse", action="store_true")
    sp.add_argument("-keys", action="store_true")
    sp.add_argument("-detailed", action="store_true")

    # catalog --------------------------------------------------------------
    sp = cmd("catalog", cmd_catalog, "catalog queries")
    sp.add_argument("what", choices=["datacenters", "nodes", "services"])

    # events / watch -------------------------------------------------------
    sp = cmd("event", cmd_event, "fire a user event")
    sp.add_argument("-name", required=True)
    sp.add_argument("payload", nargs="?", default="")
    sp = cmd("watch", cmd_watch, "watch a view for changes")
    sp.add_argument("-type", required=True, dest="wtype")
    sp.add_argument("-key", default="")
    sp.add_argument("-prefix", default="")
    sp.add_argument("-service", default="")
    sp.add_argument("-tag", default="")
    sp.add_argument("-state", default="")
    sp.add_argument("-name", default="")
    sp.add_argument("-passingonly", action="store_true")
    sp.add_argument("-once", action="store_true",
                    help="print first result and exit")

    # sessions / locks ----------------------------------------------------
    sp = cmd("session", cmd_session, "session ops")
    sp.add_argument("verb", choices=["list", "destroy", "info"])
    sp.add_argument("sid", nargs="?", default="")
    sp = cmd("lock", cmd_lock, "run a command while holding a lock")
    sp.add_argument("prefix")
    sp.add_argument("shell_command")

    # ops ------------------------------------------------------------------
    sp = cmd("acl", cmd_acl, "ACL token and policy management")
    sp.add_argument("subsystem",
                    choices=["bootstrap", "token", "policy", "role",
                             "auth-method", "binding-rule"])
    sp.add_argument("verb", nargs="?", default="list",
                    choices=["list", "create", "delete", "read"])
    sp.add_argument("arg", nargs="?", default="",
                    help="JSON definition, id, name, or secret")

    sp = cmd("login", cmd_login,
             "exchange a bearer token for a Consul token")
    sp.add_argument("-method", required=True, dest="method")
    sp.add_argument("-bearer-token", required=True, dest="bearer_token")
    sp.add_argument("-token-sink-file", default="", dest="token_sink_file")
    sp = cmd("logout", cmd_logout, "destroy the current login token")

    sp = cmd("debug", cmd_debug, "capture a debug bundle")
    sp.add_argument("-output", default="consul-debug.tar.gz")

    cmd("keygen", cmd_keygen, "generate a gossip encryption key")
    sp = cmd("keyring", cmd_keyring, "manage gossip encryption keys")
    sp.add_argument("verb", choices=["list", "install", "use", "remove"])
    sp.add_argument("key", nargs="?", default="")

    sp = cmd("snapshot", cmd_snapshot, "save/restore cluster state")
    sp.add_argument("verb", choices=["save", "restore"])
    sp.add_argument("file")

    sp = cmd("operator", cmd_operator, "cluster operator tools")
    sp.add_argument("subsystem", choices=["raft"])
    sp.add_argument("action", choices=["list-peers"])
    sp = cmd("rtt", cmd_rtt, "estimate RTT between nodes")
    sp.add_argument("node1")
    sp.add_argument("node2", nargs="?", default="")
    sp = cmd("services", cmd_services, "register/deregister agent services")
    sp.add_argument("verb", choices=["register", "deregister"])
    sp.add_argument("arg", help="JSON definition file (or '-'), or id")
    sp = cmd("monitor", cmd_monitor, "stream the agent's live logs")
    sp.add_argument("-log-level", default="info", dest="log_level")
    sp = sub.add_parser("validate", help="validate config files")
    sp.set_defaults(fn=cmd_validate)
    sp.add_argument("paths", nargs="+", help="config files or dirs")
    cmd("reload", cmd_reload, "trigger a config reload on the agent")
    sp = cmd("maint", cmd_maint, "toggle node/service maintenance mode")
    sp.add_argument("-enable", action="store_true")
    sp.add_argument("-disable", action="store_true")
    sp.add_argument("-service", default="", help="service id (node-wide "
                    "when omitted)")
    sp.add_argument("-reason", default="")

    # connect --------------------------------------------------------------
    sp = cmd("connect", cmd_connect, "service mesh tools")
    sp.add_argument("verb", choices=["proxy", "ca-rotate", "chain"])
    sp.add_argument("-sidecar-for", default="", dest="sidecar_for",
                    help="proxy id to run the built-in proxy for")
    sp.add_argument("-listen-port", type=int, default=0,
                    help="public mTLS port (defaults to the registered "
                         "service port)")
    sp.add_argument("service", nargs="?", default="",
                    help="service name (chain verb)")
    sp = cmd("intention", cmd_intention, "manage connect intentions")
    sp.add_argument("verb", choices=["create", "delete", "list", "check"])
    sp.add_argument("src", nargs="?", default="")
    sp.add_argument("dst", nargs="?", default="")
    sp.add_argument("-deny", action="store_true")

    # static analysis -----------------------------------------------------
    sp = sub.add_parser(
        "lint", help="tracelint: JAX-aware static analysis of the "
                     "simulation plane"
    )
    sp.set_defaults(fn=cmd_lint)
    sp.add_argument("paths", nargs="*",
                    help="files or directories (default: the package's "
                         "models/ sim/ ops/)")
    sp.add_argument("--rules", default="",
                    help="comma-separated rule ids, e.g. R1,R3 "
                         "(default: all)")
    sp.add_argument("--list-rules", action="store_true",
                    dest="list_rules", help="enumerate rules and exit")
    sp.add_argument("--format", choices=["text", "json"], default="text",
                    dest="format",
                    help="json: machine-readable violations object")

    sp = sub.add_parser(
        "jaxlint", help="jaxpr-level analysis of the registered "
                        "simulation entrypoints (rules J1-J6 + the "
                        "peak-HBM budget gate)"
    )
    sp.set_defaults(fn=cmd_jaxlint)
    sp.add_argument("--rules", default="",
                    help="comma-separated rule ids, e.g. J1,J6 "
                         "(default: all)")
    sp.add_argument("--list-rules", action="store_true",
                    dest="list_rules", help="enumerate rules and exit")
    sp.add_argument("--budget-gb", type=float, default=None,
                    dest="budget_gb",
                    help="per-chip HBM budget for J6 (default: 16, "
                         "one v5e chip)")
    sp.add_argument("--format", choices=["text", "json"], default="text",
                    dest="format",
                    help="json: machine-readable findings object")
    sp.add_argument("--set", choices=["small", "big", "all"],
                    default="all", dest="which",
                    help="registry slice: small-n configs, the 1M-node "
                         "configs, or both (default)")
    sp.add_argument("--module", default="",
                    help="lint JAXLINT_PROGRAMS from a Python file "
                         "instead of the engine registry")

    sp = sub.add_parser(
        "rangelint",
        help="interval-domain abstract interpretation over the "
             "registered entrypoints (rules J7-J9 + narrowing "
             "certificates)",
    )
    sp.set_defaults(fn=cmd_rangelint)
    sp.add_argument("--rules", default="",
                    help="comma-separated rule ids, e.g. J7 "
                         "(default: all)")
    sp.add_argument("--list-rules", action="store_true",
                    dest="list_rules", help="enumerate rules and exit")
    sp.add_argument("--format", choices=["text", "json"], default="text",
                    dest="format")
    sp.add_argument("--set", choices=["small", "big", "all"],
                    default="all", dest="which")
    sp.add_argument("--at-n", type=int, default=0, dest="at_n",
                    help="also read the narrowing ledger at this "
                         "population via the registry scale hooks "
                         "(e.g. 10000000)")

    sp = sub.add_parser(
        "equivlint",
        help="exactness-ladder prover + golden fingerprint gate + "
             "Pallas DMA discipline (rules E1-E3, P1-P3) over the "
             "registered entrypoints",
    )
    sp.set_defaults(fn=cmd_equivlint)
    sp.add_argument("--list-rules", action="store_true",
                    dest="list_rules", help="enumerate rules and exit")
    sp.add_argument("--format", choices=["text", "json"], default="text",
                    dest="format")
    sp.add_argument("--set", choices=["small", "big", "all"],
                    default="all", dest="which",
                    help="registry slice (default: both tiers — the "
                         "golden file covers small AND big)")
    sp.add_argument("--update-golden", action="store_true",
                    dest="update_golden",
                    help="regenerate tests/golden/programs.json from "
                         "the live fingerprints (merge: entries "
                         "outside --set are kept)")
    sp.add_argument("--golden", default="",
                    help="alternate golden snapshot path")
    sp.add_argument("--no-witness", action="store_true",
                    dest="no_witness",
                    help="structural proofs only: would-be witness "
                         "executions report SKIPPED instead of running")
    sp.add_argument("--flops", action="store_true",
                    help="include XLA cost_analysis flops in "
                         "fingerprints (lowers every program)")
    sp.add_argument("--module", default="",
                    help="lint EQUIVLINT_PROGRAMS from a Python file "
                         "instead of the engine registry (P-rules "
                         "fixture seam)")

    sp = sub.add_parser(
        "check",
        help="the umbrella pass: tracelint + jaxlint + rangelint + "
             "equivlint in one run, each registry program traced once, "
             "merged --format json, shared exit-code contract",
    )
    sp.set_defaults(fn=cmd_check)
    sp.add_argument("--format", choices=["text", "json"], default="text",
                    dest="format")
    sp.add_argument("--set", choices=["small", "big", "all"],
                    default="small", dest="which",
                    help="registry slice for the jaxpr passes "
                         "(default small; big adds the 1M configs)")
    sp.add_argument("--budget-gb", type=float, default=16.0,
                    dest="budget_gb",
                    help="per-chip HBM budget for jaxlint J6")
    sp.add_argument("--changed", action="store_true",
                    help="git-diff-aware pre-commit mode: lint/prove "
                         "only programs whose family sources changed "
                         "vs HEAD (core-plane edits widen to the full "
                         "registry)")
    sp.add_argument("--no-witness", action="store_true",
                    dest="no_witness",
                    help="equivlint structural proofs only (skip "
                         "witness executions)")

    # simulator -----------------------------------------------------------
    sp = sub.add_parser(
        "sim", help="run a TPU-simulator scenario preset"
    )
    sp.set_defaults(fn=cmd_sim)
    sp.add_argument("scenario", nargs="?", default="",
                    help="preset name (see --list)")
    sp.add_argument("--list", action="store_true", dest="list_scenarios",
                    help="enumerate scenario presets and exit")
    sp.add_argument("-seed", type=int, default=0)
    sp.add_argument("--devices", type=int, default=0,
                    help="shard the scenario's node axis over the first "
                         "D devices (consul_tpu/parallel/shard.py; on "
                         "CPU containers force host devices with "
                         "XLA_FLAGS=--xla_force_host_platform_device_"
                         "count=D)")
    sp.add_argument("--exchange", default="",
                    choices=("", "alltoall", "ring"),
                    help="outbox transport of the sharded plane "
                         "(requires --devices): 'alltoall' = one XLA "
                         "collective per round, 'ring' = the Pallas "
                         "make_async_remote_copy DMA kernel "
                         "(consul_tpu/ops/ring_exchange.py); backends "
                         "are bit-equal")
    sp.add_argument("--metrics", action="store_true", dest="metrics",
                    help="run the study with the in-scan telemetry "
                         "seam on (consul_tpu/obs) and print the "
                         "bridged /v1/agent/metrics-shaped snapshot "
                         "under \"metrics\"")
    sp.add_argument("--policy", default="",
                    choices=("",) + SIM_POLICY_CHOICES,
                    help="chunk-selection schedule of the streamcast "
                         "plane (stream100k only; other presets "
                         "reject it loudly): 'uniform' = random held "
                         "chunk (the original program), 'pipeline' = "
                         "the round-robin cursor schedule of the "
                         "pipelined-gossiping paper, 'rarest' = "
                         "greedy lowest-index")

    sp = sub.add_parser(
        "profile",
        help="XLA cost/profile harness over the jaxlint registry "
             "(consul_tpu/obs/profile.py): cost_analysis flops/bytes "
             "+ compile-vs-execute wall split per entrypoint",
    )
    sp.set_defaults(fn=cmd_profile)
    sp.add_argument("--set", default="small", dest="which",
                    choices=("small", "big", "all"),
                    help="registry tier to profile (default small; "
                         "big = the 1M-node bench shapes)")
    sp.add_argument("--entry", default="",
                    help="profile only registry entries whose name "
                         "contains this substring")
    sp.add_argument("--execute", action="store_true",
                    help="also execute each compiled program once on "
                         "zero states and report execute-wall "
                         "(analyses alone allocate nothing)")
    sp.add_argument("--perfetto", default="", metavar="DIR",
                    help="additionally run one small telemetry=on "
                         "study under jax.profiler.trace(DIR) for "
                         "perfetto/tensorboard trace capture (on-TPU "
                         "trace capture path)")
    sp.add_argument("--format", choices=("text", "json"),
                    default="text")

    sp = sub.add_parser(
        "sweep", help="run a universe-sweep preset: U (seed, knob, "
                      "fault) universes vmapped into ONE XLA program "
                      "(consul_tpu/sweep)"
    )
    sp.set_defaults(fn=cmd_sweep)
    sp.add_argument("preset", nargs="?", default="",
                    help="preset name (see --list)")
    sp.add_argument("--list", action="store_true", dest="list_presets",
                    help="enumerate sweep presets and exit")
    sp.add_argument("--universes", type=int, default=None,
                    help="universe count U (seed presets only; grid "
                         "presets derive U from their ladders)")
    sp.add_argument("-seed", type=int, default=0)
    sp.add_argument("--frontier-x", default="", dest="frontier_x",
                    help="robustness metric of the Pareto frontier "
                         "(default: preset-appropriate)")
    sp.add_argument("--frontier-y", default="", dest="frontier_y",
                    help="latency metric of the Pareto frontier")
    sp.add_argument("--devices", type=int, default=None,
                    help="compose the sweep with the nodes mesh: U "
                         "universes x n/D nodes per device in ONE "
                         "program (sweep x shard; sharded-twin "
                         "entrypoints only)")
    sp.add_argument("--exchange", default="alltoall",
                    choices=("alltoall", "ring"),
                    help="outbox transport of a composed sweep "
                         "(requires --devices)")
    sp.add_argument("--optimize", action="store_true",
                    help="close the loop: successive-halving/"
                         "bisection over the preset's knob ladders "
                         "instead of evaluating its fixed grid "
                         "(consul_tpu/sweep/optimize.py)")
    sp.add_argument("--objective", default="",
                    help="metric to optimize (--optimize; validated "
                         "against the entrypoint's metric registry)")
    sp.add_argument("--minimize", action="store_true",
                    help="minimize the objective (default: maximize)")
    sp.add_argument("--knee-at", type=float, default=None,
                    dest="knee_at",
                    help="knee mode: find the largest knob value "
                         "whose objective stays <= this threshold "
                         "(e.g. --objective window_overflow "
                         "--knee-at 0)")
    sp.add_argument("--points-per-gen", type=int, default=None,
                    dest="points_per_gen",
                    help="universes per optimizer generation (U stays "
                         "constant so generations never retrace)")
    sp.add_argument("--max-generations", type=int, default=12,
                    dest="max_generations")

    # Like the reference, version tolerates (and ignores) the global
    # client flags so scripted `cli ... -http-addr X` loops can include
    # it (sdk/testutil TestServer drives every command the same way).
    cmd("version", cmd_version, "print the CLI version")
    return p


# ---------------------------------------------------------------------------
# agent
# ---------------------------------------------------------------------------


def build_runtime(args):
    """Files + flags → RuntimeConfig (the CLI half of config/builder.go:
    -config-file/-config-dir in order, flags last)."""
    from consul_tpu.agent.config import Builder

    b = Builder()
    for path in args.config_file:
        b.add_file(path)
    for path in args.config_dir:
        b.add_dir(path)
    flags = {
        "node_name": args.node or None,
        "datacenter": args.datacenter,
        "server": True if (args.server or args.dev) else None,
        "bootstrap_expect": 1 if args.dev else args.bootstrap_expect,
        "bind_addr": args.bind,
        "ports_http": args.http_port,
        "ports_dns": args.dns_port,
        "data_dir": args.data_dir,
    }
    b.add_flags(flags)
    rc = b.build()
    if not args.node and rc.node_name == "node" and args.dev:
        rc = __import__("dataclasses").replace(rc, node_name="dev")
    return rc


async def cmd_agent(args) -> int:
    from consul_tpu.agent import Agent, AgentConfig
    from consul_tpu.agent.config import reloadable_diff, thaw
    from consul_tpu.agent.dns import DNSServer
    from consul_tpu.agent.http import HTTPApi
    from consul_tpu.net.transport import UDPTransport

    rc = build_runtime(args)
    node = rc.node_name
    server_mode = rc.server

    gossip = UDPTransport(rc.bind_addr, args.serf_port)
    rpc = UDPTransport(rc.bind_addr, args.rpc_port)
    await gossip.start()
    await rpc.start()
    agent = Agent(
        AgentConfig(
            node_name=node,
            datacenter=rc.datacenter,
            server=server_mode,
            bootstrap_expect=rc.bootstrap_expect,
            profile=rc.gossip_profile(),
            gossip_interval_scale=rc.gossip_interval_scale,
            acl_enabled=rc.acl_enabled,
            acl_default_policy=rc.acl_default_policy,
            acl_master_token=rc.acl_master_token,
            acl_agent_token=rc.acl_agent_token,
            encrypt_key=rc.encrypt,
            primary_datacenter=rc.primary_datacenter,
            acl_replication_token=rc.acl_replication_token,
            serf_snapshot_path=(
                str(Path(rc.data_dir) / "serf" / "local.snapshot")
                if rc.data_dir and server_mode
                else ""
            ),
            rejoin_after_leave=rc.rejoin_after_leave,
            auto_config_enabled=rc.auto_config_enabled,
            auto_config_intro_token=rc.auto_config_intro_token,
            auto_config_server_addresses=tuple(
                rc.auto_config_server_addresses),
            auto_config_authorizer=rc.auto_config_authorizer,
        ),
        gossip_transport=gossip,
        rpc_transport=rpc,
    )
    await agent.start()
    agent.load_definitions(
        [thaw(s) for s in rc.services], [thaw(c) for c in rc.checks]
    )
    agent.dns_only_passing = rc.dns_only_passing
    agent.dns_node_ttl_s = rc.dns_node_ttl_s
    agent.dns_recursors = list(rc.dns_recursors)
    api = HTTPApi(agent)
    http_addr = await api.start(rc.bind_addr, rc.ports_http)
    dns = DNSServer(agent)
    dns_addr = await dns.start(rc.bind_addr, rc.ports_dns)

    # SIGHUP: re-read the same sources, apply the reloadable subset
    # (agent.go reloadConfigInternal).
    def on_hup():
        """Returns None on success, the error on failure — the HTTP
        reload endpoint surfaces it to the caller (agent_endpoint.go
        AgentReload returns the error); SIGHUP just logs it."""
        nonlocal rc
        try:
            new_rc = build_runtime(args)
            apply = reloadable_diff(rc, new_rc)
            agent.reload(apply)
            rc = new_rc
            print(f"==> Reloaded configuration ({len(apply)} change(s))")
        except Exception as e:  # noqa: BLE001 - keep running on bad config
            print(f"==> Reload failed: {e}", file=sys.stderr)
            sys.stdout.flush()
            return e
        sys.stdout.flush()
        return None

    try:
        asyncio.get_running_loop().add_signal_handler(signal.SIGHUP, on_hup)
    except (NotImplementedError, AttributeError):  # pragma: no cover
        pass
    # PUT /v1/agent/reload triggers the same path as SIGHUP.
    agent.reload_handler = on_hup

    print("==> consul-tpu agent running!")
    print(f"         Node name: {node}")
    print(f"        Datacenter: {rc.datacenter}")
    print(f"            Server: {server_mode}")
    print(f"         HTTP addr: {http_addr}")
    print(f"          DNS addr: {dns_addr} (udp)")
    print(f"        Gossip via: {gossip.local_addr()}")
    print(f"          RPC addr: {rpc.local_addr()}")
    sys.stdout.flush()

    join_addrs = list(args.join) + [str(a) for a in rc.retry_join]
    if join_addrs:
        n = await agent.join(join_addrs)
        print(f"==> Joined {n} node(s)")
        sys.stdout.flush()

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # pragma: no cover - non-unix
            pass
    await stop.wait()
    print("==> Caught signal, gracefully leaving")
    await agent.leave()
    await api.stop()
    await dns.stop()
    await agent.shutdown()
    return 0


# ---------------------------------------------------------------------------
# client commands
# ---------------------------------------------------------------------------


def _client(args) -> ConsulClient:
    return ConsulClient(args.http_addr, token=getattr(args, "token", ""))


async def cmd_members(args) -> int:
    members = await _client(args).agent.members()
    status_names = {0: "none", 1: "alive", 2: "leaving", 3: "left",
                    4: "failed"}
    rows = [("Node", "Address", "Status", "Type", "DC")]
    for m in sorted(members, key=lambda m: m["Name"]):
        tags = m.get("Tags", {})
        rows.append((
            m["Name"], m["Addr"],
            status_names.get(m["Status"], str(m["Status"])),
            "server" if tags.get("role") == "consul" else "client",
            tags.get("dc", ""),
        ))
    _print_table(rows)
    return 0


async def cmd_join(args) -> int:
    c = _client(args)
    for addr in args.addresses:
        out = await c.agent.join(addr)
        print(f"Successfully joined cluster by contacting "
              f"{out.get('NumJoined', 0)} nodes.")
    return 0


async def cmd_leave(args) -> int:
    await _client(args).agent.leave()
    print("Graceful leave complete")
    return 0


async def cmd_force_leave(args) -> int:
    await _client(args).agent.force_leave(args.node)
    print(f"Force-left {args.node}")
    return 0


async def cmd_info(args) -> int:
    c = _client(args)
    self_info = await c.agent.self()
    leader = await c.status.leader()
    peers = await c.status.peers()
    print(json.dumps({"agent": self_info, "leader": leader,
                      "peers": peers}, indent=2, default=str))
    return 0


async def cmd_kv(args) -> int:
    c = _client(args)
    if args.verb == "get":
        if args.keys:
            keys, _ = await c.kv.keys(args.key)
            print("\n".join(keys))
        elif args.recurse:
            entries, _ = await c.kv.list(args.key)
            for e in entries:
                print(f"{e['Key']}:{e['Value'].decode(errors='replace')}")
        else:
            entry, _ = await c.kv.get(args.key)
            if entry is None:
                print(f"Error! No key exists at: {args.key}", file=sys.stderr)
                return 1
            if args.detailed:
                print(json.dumps(
                    {k: v for k, v in entry.items() if k != "Value"},
                    indent=2))
            print(entry["Value"].decode(errors="replace"))
    elif args.verb == "put":
        value = (args.value or "").encode()
        if args.value and args.value.startswith("@"):
            with open(args.value[1:], "rb") as f:
                value = f.read()
        await c.kv.put(args.key, value)
        print(f"Success! Data written to: {args.key}")
    elif args.verb == "delete":
        await c.kv.delete(args.key, recurse=args.recurse)
        print(f"Success! Deleted key: {args.key}")
    elif args.verb == "export":
        entries, _ = await c.kv.list(args.key)
        out = [{"key": e["Key"], "flags": e.get("Flags", 0),
                "value": base64.b64encode(e["Value"]).decode()}
               for e in entries]
        print(json.dumps(out, indent=2))
    elif args.verb == "import":
        data = json.loads(sys.stdin.read())
        for item in data:
            await c.kv.put(item["key"], base64.b64decode(item["value"]),
                           flags=item.get("flags", 0))
        print(f"Imported: {len(data)} keys")
    return 0


async def cmd_catalog(args) -> int:
    c = _client(args)
    if args.what == "datacenters":
        print("\n".join(await c.catalog.datacenters()))
    elif args.what == "nodes":
        nodes, _ = await c.catalog.nodes()
        rows = [("Node", "Address")]
        rows += [(n["Node"], n["Address"]) for n in nodes]
        _print_table(rows)
    elif args.what == "services":
        services, _ = await c.catalog.services()
        rows = [("Service", "Tags")]
        rows += [(name, ",".join(tags)) for name, tags in sorted(
            services.items())]
        _print_table(rows)
    return 0


async def cmd_event(args) -> int:
    out = await _client(args).event.fire(args.name, args.payload.encode())
    print(f"Event ID: {out['ID']}")
    return 0


async def cmd_watch(args) -> int:
    params = {"type": args.wtype}
    for field in ("key", "prefix", "service", "tag", "state", "name"):
        if getattr(args, field):
            params[field] = getattr(args, field)
    if args.passingonly:
        params["passingonly"] = True
    plan = parse_watch(params, _client(args))
    done = asyncio.Event()

    def handler(index, data):
        print(json.dumps({"index": index, "data": data}, indent=2,
                         default=_json_bytes))
        sys.stdout.flush()
        if args.once:
            done.set()

    plan.on_change(handler)
    plan.start()
    if args.once:
        await done.wait()
    else:
        await asyncio.Event().wait()  # until Ctrl-C
    plan.stop()
    return 0


async def cmd_session(args) -> int:
    c = _client(args)
    if args.verb == "list":
        sessions, _ = await c.session.list()
        rows = [("ID", "Node", "TTL", "Behavior")]
        rows += [(s["ID"], s["Node"], str(s.get("TTL", "")),
                  s.get("Behavior", "")) for s in sessions]
        _print_table(rows)
    elif args.verb == "destroy":
        await c.session.destroy(args.sid)
        print(f"Destroyed session {args.sid}")
    elif args.verb == "info":
        sess, _ = await c.session.info(args.sid)
        print(json.dumps(sess, indent=2))
    return 0


async def cmd_lock(args) -> int:
    """command/lock: acquire <prefix>/.lock with a session, run the
    command, release (reference lock command semantics)."""
    c = _client(args)
    sid = await c.session.create({"TTL": "15s"})
    key = f"{args.prefix.rstrip('/')}/.lock"
    try:
        while not await c.kv.put(key, b"", acquire=sid):
            await asyncio.sleep(0.2)
        proc = await asyncio.create_subprocess_shell(args.shell_command)
        renew = asyncio.create_task(_renew_loop(c, sid))
        code = await proc.wait()
        renew.cancel()
        return code
    finally:
        try:
            await c.kv.put(key, b"", release=sid)
            await c.session.destroy(sid)
        except Exception:  # noqa: BLE001 — best effort cleanup
            pass


async def _renew_loop(c: ConsulClient, sid: str) -> None:
    while True:
        await asyncio.sleep(5)
        await c.session.renew(sid)


async def cmd_acl(args) -> int:
    """command/acl: bootstrap, token list/create/delete, policy ..."""
    c = _client(args)
    if args.subsystem == "bootstrap":
        tok = await c.acl.bootstrap()
        print(f"SecretID: {tok['SecretID']}")
        return 0
    import json as _json

    if args.subsystem == "token":
        if args.verb == "list":
            for t in await c.acl.token_list():
                print(f"{t.get('SecretID', '')}\t{t.get('Type', '')}\t"
                      f"{t.get('Description', '')}")
        elif args.verb == "create":
            tok = await c.acl.token_create(
                _json.loads(args.arg) if args.arg else {}
            )
            print(f"SecretID: {tok['SecretID']}")
        elif args.verb == "read":
            tok = await c.acl.token_read(args.arg)
            print(_json.dumps(tok, indent=2))
        else:
            await c.acl.token_delete(args.arg)
            print("deleted")
        return 0
    if args.subsystem == "role":
        if args.verb == "list":
            for r in await c.acl.role_list():
                print(f"{r.get('ID', '')}\t{r.get('Name', '')}")
        elif args.verb == "create":
            r = await c.acl.role_create(_json.loads(args.arg))
            print(f"ID: {r['ID']}")
        elif args.verb == "read":
            r = await c.acl.role_read(name=args.arg)
            print(_json.dumps(r, indent=2))
        else:
            await c.acl.role_delete(args.arg)
            print("deleted")
        return 0
    if args.subsystem == "auth-method":
        if args.verb == "list":
            for mth in await c.acl.auth_method_list():
                print(f"{mth.get('Name', '')}\t{mth.get('Type', '')}")
        elif args.verb == "create":
            mth = await c.acl.auth_method_create(_json.loads(args.arg))
            print(f"Name: {mth['Name']}")
        elif args.verb == "read":
            mth = await c.acl.auth_method_read(args.arg)
            print(_json.dumps(mth, indent=2))
        else:
            await c.acl.auth_method_delete(args.arg)
            print("deleted")
        return 0
    if args.subsystem == "binding-rule":
        if args.verb == "list":
            for br in await c.acl.binding_rule_list():
                print(f"{br.get('ID', '')}\t{br.get('AuthMethod', '')}\t"
                      f"{br.get('BindType', '')}:{br.get('BindName', '')}")
        elif args.verb == "create":
            br = await c.acl.binding_rule_create(_json.loads(args.arg))
            print(f"ID: {br['ID']}")
        elif args.verb == "read":
            br = await c.acl.binding_rule_read(args.arg)
            print(_json.dumps(br, indent=2))
        else:
            await c.acl.binding_rule_delete(args.arg)
            print("deleted")
        return 0
    if args.verb == "list":
        for pl in await c.acl.policy_list():
            print(f"{pl.get('ID', '')}\t{pl.get('Name', '')}")
    elif args.verb == "create":
        pl = await c.acl.policy_create(_json.loads(args.arg))
        print(f"ID: {pl['ID']}")
    elif args.verb == "read":
        pl = await c.acl.policy_read(args.arg)
        print(_json.dumps(pl, indent=2))
    else:
        await c.acl.policy_delete(args.arg)
        print("deleted")
    return 0


async def cmd_login(args) -> int:
    """command/login: exchange an auth-method bearer token for a
    Consul token (command/acl/authmethod login.go)."""
    c = _client(args)
    tok = await c.acl.login(args.method, args.bearer_token)
    secret = tok.get("SecretID", "")
    if args.token_sink_file:
        import os as _os
        fd = _os.open(args.token_sink_file,
                      _os.O_WRONLY | _os.O_CREAT | _os.O_TRUNC, 0o600)
        with _os.fdopen(fd, "w") as f:
            f.write(secret)
        print(f"token written to {args.token_sink_file}")
    else:
        print(f"SecretID: {secret}")
    return 0


async def cmd_validate(args) -> int:
    """command/validate: parse + validate config sources without
    starting an agent (config/builder.go Validate)."""
    from pathlib import Path as _Path

    from consul_tpu.agent.config import Builder, ConfigError

    b = Builder()
    try:
        for path in args.paths:
            if _Path(path).is_dir():
                b.add_dir(path)
            else:
                b.add_file(path)
        b.build()
    except (ConfigError, OSError, ValueError) as e:
        print(f"Config validation failed: {e}", file=sys.stderr)
        return 1
    print("Configuration is valid!")
    return 0


async def cmd_reload(args) -> int:
    """command/reload: PUT /v1/agent/reload (agent_endpoint.go
    AgentReload) — same effect as SIGHUP."""
    await _client(args).write("PUT", "/v1/agent/reload")
    print("Configuration reload triggered")
    return 0


async def cmd_maint(args) -> int:
    """command/maint: service or node maintenance toggle
    (agent.go:3411 EnableServiceMaintenance)."""
    if args.enable == args.disable:
        print("exactly one of -enable / -disable is required",
              file=sys.stderr)
        return 1
    c = _client(args)
    params = {"enable": "true" if args.enable else "false"}
    if args.reason:
        params["reason"] = args.reason
    if args.service:
        path = f"/v1/agent/service/maintenance/{args.service}"
    else:
        path = "/v1/agent/maintenance"
    await c.write("PUT", path, params=params)
    print("maintenance " + ("enabled" if args.enable else "disabled"))
    return 0


async def cmd_logout(args) -> int:
    """command/logout: destroy the login token in use."""
    c = _client(args)
    await c.acl.logout()
    print("logged out")
    return 0


async def cmd_debug(args) -> int:
    """command/debug: capture agent state (self, members, metrics,
    host) into a tar.gz bundle for offline analysis."""
    import io
    import tarfile
    import time as _time

    c = _client(args)
    captures = {}
    for name, path in (
        ("self.json", "/v1/agent/self"),
        ("members.json", "/v1/agent/members"),
        ("metrics.json", "/v1/agent/metrics"),
        ("host.json", "/v1/agent/host"),
    ):
        status, _, data = await c.request("GET", path)
        captures[name] = json.dumps(
            data if status == 200 else {"error": status}, indent=2,
            default=str,
        ).encode()
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for name, data in captures.items():
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = int(_time.time())
            tar.addfile(info, io.BytesIO(data))
    with open(args.output, "wb") as fh:
        fh.write(buf.getvalue())
    print(f"Saved debug bundle to {args.output}")
    return 0


async def cmd_keygen(args) -> int:
    """command/keygen: a fresh 32-byte key, base64."""
    from consul_tpu.net.security import generate_key

    print(generate_key())
    return 0


async def cmd_keyring(args) -> int:
    """command/keyring: -list/-install/-use/-remove over
    /v1/operator/keyring."""
    c = _client(args)
    method = {"list": "GET", "install": "POST", "use": "PUT",
              "remove": "DELETE"}[args.verb]
    body = {"Key": args.key} if args.verb != "list" else None
    status, _, data = await c.request(method, "/v1/operator/keyring",
                                      body=body)
    if status != 200:
        print(f"Error: HTTP {status}: {data}", file=sys.stderr)
        return 1
    print(json.dumps(data, indent=2, default=str))
    return 0


async def cmd_snapshot(args) -> int:
    """command/snapshot: save streams the archive to disk, restore
    uploads and installs it (inspect via the SHA256SUMS manifest)."""
    c = _client(args)
    if args.verb == "save":
        status, _, data = await c.request("GET", "/v1/snapshot")
        if status != 200:
            print(f"Error: HTTP {status}: {data}", file=sys.stderr)
            return 1
        with open(args.file, "wb") as fh:
            fh.write(data if isinstance(data, bytes) else bytes(data))
        print(f"Saved snapshot to {args.file}")
        return 0
    with open(args.file, "rb") as fh:
        blob = fh.read()
    status, _, data = await c.request("PUT", "/v1/snapshot", raw_body=blob)
    if status != 200:
        print(f"Error: HTTP {status}: {data}", file=sys.stderr)
        return 1
    print("Restored snapshot")
    return 0


async def cmd_operator(args) -> int:
    out = await _client(args).operator.raft_configuration()
    rows = [("Node", "Address", "State", "Voter")]
    for s in out.get("Servers", []):
        rows.append((s["ID"], s["Address"],
                     "leader" if s["Leader"] else "follower",
                     str(s["Voter"]).lower()))
    _print_table(rows)
    return 0


async def cmd_rtt(args) -> int:
    """command/rtt: Vivaldi distance between two nodes' coordinates."""
    c = _client(args)
    node2 = args.node2
    if not node2:
        self_info = await c.agent.self()
        node2 = self_info["Config"]["NodeName"]
    c1, _ = await c.coordinate.node(args.node1)
    c2, _ = await c.coordinate.node(node2)
    if not c1 or not c2:
        print("Error: coordinates not yet available", file=sys.stderr)
        return 1
    rtt = _coord_distance(c1[0]["Coord"], c2[0]["Coord"])
    print(f"Estimated {args.node1} <-> {node2} rtt: {rtt * 1000:.3f} ms")
    return 0


def _coord_distance(a: dict, b: dict) -> float:
    """coordinate.Coordinate.DistanceTo (Vivaldi 8-D + height)."""
    vec_a, vec_b = a.get("Vec", []), b.get("Vec", [])
    dist = math.sqrt(sum((x - y) ** 2 for x, y in zip(vec_a, vec_b)))
    dist += a.get("Height", 0.0) + b.get("Height", 0.0)
    adjusted = dist + a.get("Adjustment", 0.0) + b.get("Adjustment", 0.0)
    return max(adjusted, 0.0)


async def cmd_services(args) -> int:
    c = _client(args)
    if args.verb == "register":
        raw = sys.stdin.read() if args.arg == "-" else open(args.arg).read()
        await c.agent.service_register(json.loads(raw))
        print("Registered service")
    else:
        await c.agent.service_deregister(args.arg)
        print(f"Deregistered service: {args.arg}")
    return 0


async def cmd_monitor(args) -> int:
    """Stream the agent's live logs (command/monitor → chunked
    /v1/agent/monitor, agent_endpoint.go:1140)."""
    from consul_tpu.api.client import APIError

    c = _client(args)
    try:
        async for chunk in c.stream(
            f"/v1/agent/monitor?loglevel={args.log_level}"
        ):
            sys.stdout.write(chunk.decode(errors="replace"))
            sys.stdout.flush()
    except APIError as e:
        print(f"monitor failed: {e}", file=sys.stderr)
        return 1
    except (asyncio.IncompleteReadError, KeyboardInterrupt):
        pass
    return 0


async def cmd_connect(args) -> int:
    """connect subcommands (command/connect): run the built-in sidecar
    proxy, rotate the CA, or print a compiled discovery chain."""
    c = _client(args)
    if args.verb == "ca-rotate":
        out = await c.write("PUT", "/v1/connect/ca/rotate")
        print(f"New active root: {out.get('RootID', '')}")
        return 0
    if args.verb == "chain":
        if not args.service:
            print("Error: chain requires a service name", file=sys.stderr)
            return 1
        out, _ = await c.read(f"/v1/discovery-chain/{args.service}")
        print(json.dumps(out, indent=2, default=_json_bytes))
        return 0
    # proxy: run until interrupted (connect/proxy/proxy.go main loop).
    if not args.sidecar_for:
        print("Error: -sidecar-for is required", file=sys.stderr)
        return 1
    from consul_tpu.connect.proxy import ConnectProxy

    port = args.listen_port
    if not port:
        services = await c.agent.services()
        svc = services.get(args.sidecar_for)
        if svc is None:
            print(f"Error: no registered service {args.sidecar_for!r}",
                  file=sys.stderr)
            return 1
        port = int(svc.get("Port", 0))
    proxy = await ConnectProxy(args.sidecar_for, args.http_addr,
                               public_port=port).start()
    print(f"==> proxy for {args.sidecar_for} listening "
          f"(public mTLS {proxy.public_addr})")
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError):
            pass
    await stop.wait()
    await proxy.stop()
    return 0


async def cmd_intention(args) -> int:
    """intention subcommands (command/intention)."""
    c = _client(args)
    if args.verb == "list":
        out, _ = await c.read("/v1/connect/intentions")
        rows = [("ID", "Source", "Destination", "Action")]
        for i in out or []:
            rows.append((i.get("ID", "")[:8], i.get("Source", ""),
                         i.get("Destination", ""), i.get("Action", "")))
        _print_table(rows)
        return 0
    if not args.src or not args.dst:
        print("Error: need SRC and DST", file=sys.stderr)
        return 1
    if args.verb == "create":
        out = await c.write("POST", "/v1/connect/intentions", body={
            "Source": args.src, "Destination": args.dst,
            "Action": "deny" if args.deny else "allow",
        })
        print(f"Created: {out.get('ID', '')}")
        return 0
    if args.verb == "check":
        out, _ = await c.read(
            "/v1/connect/intentions/check",
            params={"source": args.src, "target": args.dst})
        print("Allowed" if out.get("Authorized") else "Denied")
        return 0 if out.get("Authorized") else 2
    # delete: find by pair.
    out, _ = await c.read("/v1/connect/intentions")
    for i in out or []:
        if i.get("Source") == args.src and i.get("Destination") == args.dst:
            await c.write("DELETE", f"/v1/connect/intentions/{i['ID']}")
            print(f"Deleted: {i['ID']}")
            return 0
    print("Error: no such intention", file=sys.stderr)
    return 1


async def cmd_lint(args) -> int:
    """tracelint over the simulation plane (consul_tpu.analysis): exits
    nonzero on violations, printing clickable ``file:line:col rule
    message`` lines.  Pure AST work — no JAX import, so the command
    runs in accelerator-free containers."""
    from consul_tpu.analysis.tracelint import main as tracelint_main

    argv = list(args.paths)
    if args.list_rules:
        argv.append("--list-rules")
    if args.rules:
        argv.extend(["--rules", args.rules])
    if getattr(args, "format", "text") != "text":
        argv.extend(["--format", args.format])
    return tracelint_main(argv)


async def cmd_jaxlint(args) -> int:
    """jaxpr-level lint over the registered simulation entrypoints
    (consul_tpu.analysis.jaxlint): traces each program abstractly —
    eval_shape states, make_jaxpr programs, no device memory — and
    exits nonzero on any J1-J6 finding, mirroring ``cli lint``'s
    contract.  Needs JAX; jaxlint.main forces 8 virtual CPU devices
    when the backend is uninitialized so the sharded D=2 entries lint
    on single-device hosts."""
    from consul_tpu.analysis.jaxlint import main as jaxlint_main

    argv = []
    if args.list_rules:
        argv.append("--list-rules")
    if args.rules:
        argv.extend(["--rules", args.rules])
    if args.budget_gb is not None:
        argv.extend(["--budget-gb", str(args.budget_gb)])
    if args.format != "text":
        argv.extend(["--format", args.format])
    if args.which != "all":
        argv.extend(["--set", args.which])
    if args.module:
        argv.extend(["--module", args.module])
    return jaxlint_main(argv)


async def cmd_rangelint(args) -> int:
    """Interval-domain analysis over the registered entrypoints
    (consul_tpu.analysis.rangelint): J7 overflow certification + the
    narrowing-certificate ledger, J8 PRNG key lineage, J9 loud
    accounting.  Mirrors ``cli jaxlint``'s exit-code contract."""
    from consul_tpu.analysis.rangelint import main as rangelint_main

    argv = []
    if args.list_rules:
        argv.append("--list-rules")
    if args.rules:
        argv.extend(["--rules", args.rules])
    if args.format != "text":
        argv.extend(["--format", args.format])
    if args.which != "all":
        argv.extend(["--set", args.which])
    if args.at_n:
        argv.extend(["--at-n", str(args.at_n)])
    return rangelint_main(argv)


async def cmd_equivlint(args) -> int:
    """Exactness-ladder prover over the declared EQUIV_PAIRS (E1),
    golden program-fingerprint gate (E2/E3), and Pallas DMA-discipline
    rules (P1-P3) — consul_tpu.analysis.equivlint.  Exit-code contract
    mirrors ``cli jaxlint``: nonzero on any FAILED verdict, golden
    diff, or Pallas finding."""
    from consul_tpu.analysis.equivlint import main as equivlint_main

    argv = []
    if args.list_rules:
        argv.append("--list-rules")
    if args.format != "text":
        argv.extend(["--format", args.format])
    argv.extend(
        ["--set", "small,big" if args.which == "all" else args.which]
    )
    if args.update_golden:
        argv.append("--update-golden")
    if args.golden:
        argv.extend(["--golden", args.golden])
    if args.no_witness:
        argv.append("--no-witness")
    if args.flops:
        argv.append("--flops")
    if args.module:
        argv.extend(["--module", args.module])
    return equivlint_main(argv)


async def cmd_check(args) -> int:
    """The umbrella subcommand: tracelint + jaxlint + rangelint in one
    pass (each registry program traced ONCE, shared by both jaxpr
    passes), with merged ``--format json`` output and the shared
    exit-code contract (0 clean, 1 findings)."""
    import os as _os

    from consul_tpu.analysis.jaxlint import _backend_initialized

    if not _backend_initialized():
        _os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = _os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            _os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    from consul_tpu.analysis import run_check

    include = (
        ("small", "big") if args.which == "all" else (args.which,)
    )
    out = run_check(include=include, budget_gb=args.budget_gb,
                    changed=args.changed,
                    witness=not args.no_witness)
    if args.format == "json":
        print(json.dumps(out))
        return 0 if out["clean"] else 1
    for v in out["tracelint"]["violations"]:
        print(f"{v['path']}:{v['line']}:{v['col']} {v['rule']} "
              f"{v['message']}")
    for key in ("jaxlint", "rangelint", "equivlint"):
        for f in out[key]["findings"]:
            where = f["where"] or "<program>"
            print(f"{f['program']}: {where} {f['rule']} {f['message']}")
    el = out["equivlint"]
    n_bad = (len(out["tracelint"]["violations"])
             + len(out["jaxlint"]["findings"])
             + len(out["rangelint"]["findings"])
             + len(el["findings"]))
    walls = ", ".join(
        f"{k} {v}s" for k, v in out["wall_s"].items()
    )
    n_certs = sum(
        1 for cs in out["rangelint"]["certificates"].values()
        for c in cs if c["saved_bytes"] > 0
    )
    print(
        f"check: {'clean' if out['clean'] else f'{n_bad} finding(s)'} "
        f"({out['tracelint']['files']} file(s), "
        f"{out['jaxlint']['programs']} program(s), "
        f"{n_certs} narrowing certificate(s), "
        f"{el['proved']} proved + {el['witnessed']} witnessed of "
        f"{el['pairs']} pair(s), {el['golden_diffs']} golden diff(s); "
        f"{walls})",
        file=sys.stderr,
    )
    return 0 if out["clean"] else 1


async def cmd_sim(args) -> int:
    """Run (or enumerate) the simulator's scenario presets — the only
    CLI command that touches JAX, so the import stays local and every
    other subcommand remains accelerator-free."""
    from consul_tpu.sim.scenarios import SCENARIOS, run_scenario

    if args.list_scenarios:
        for name in sorted(SCENARIOS):
            doc = (SCENARIOS[name].__doc__ or "").strip().splitlines()
            first = doc[0].strip() if doc else ""
            print(f"{name:<12} {first}")
        return 0
    if not args.scenario:
        print("Error: scenario name required (or --list)", file=sys.stderr)
        return 1
    out = run_scenario(args.scenario, seed=args.seed,
                       devices=args.devices or None,
                       exchange=args.exchange or None,
                       telemetry=args.metrics,
                       policy=args.policy or None)
    print(json.dumps(out, indent=2, default=str))
    return 0


async def cmd_profile(args) -> int:
    """XLA cost/profile harness (consul_tpu/obs/profile.py): lower +
    compile each registered entrypoint and print what XLA reports —
    cost_analysis flops/bytes-accessed, the memory census, and the
    trace/compile(/execute) wall split.  JAX import stays local, like
    ``cli sim``."""
    from consul_tpu.obs.profile import profile_registry, run_with_profiler
    from consul_tpu.sim.engine import jaxlint_registry

    include = (
        ("small", "big") if args.which == "all" else (args.which,)
    )
    programs = jaxlint_registry(include=include)
    if args.entry:
        programs = {
            k: v for k, v in programs.items() if args.entry in k
        }
        if not programs:
            print(f"Error: no registry entry matches {args.entry!r}",
                  file=sys.stderr)
            return 1
    profiles = profile_registry(programs, execute=args.execute)
    if args.perfetto:
        # One small telemetry=on study under the profiler: the on-TPU
        # trace-capture path (perfetto UI / tensorboard profile).
        from consul_tpu.models.broadcast import BroadcastConfig
        from consul_tpu.sim.engine import run_broadcast

        run_with_profiler(
            args.perfetto,
            lambda: run_broadcast(
                BroadcastConfig(n=4096, fanout=4, delivery="edges"),
                steps=30, warmup=True, telemetry=True,
            ),
        )
        print(f"perfetto trace written under {args.perfetto}",
              file=sys.stderr)
    if args.format == "json":
        print(json.dumps({"programs": [p.to_json() for p in profiles]}))
        return 0
    rows = [("PROGRAM", "FLOPS", "BYTES", "TRACE_S", "COMPILE_S",
             "EXECUTE_S")]
    for p in profiles:
        rows.append((
            p.name,
            "-" if p.flops is None else f"{p.flops:.3g}",
            "-" if p.bytes_accessed is None else f"{p.bytes_accessed:.3g}",
            f"{p.trace_s:.2f}",
            f"{p.compile_s:.2f}",
            (f"{p.execute_s:.3f}" if p.execute_s is not None
             else (p.execute_skipped or "-")),
        ))
    _print_table(rows)
    return 0


async def cmd_sweep(args) -> int:
    """Run (or enumerate) the universe-sweep presets — like ``cli
    sim``, the JAX import stays local so every other subcommand remains
    accelerator-free.  The summary JSON carries universes/sec, the
    per-universe metric stats, and the robustness/latency Pareto
    frontier when the preset defines both axes."""
    from consul_tpu.sweep.presets import PRESETS, make_preset

    if args.list_presets:
        for name in sorted(PRESETS):
            doc = (PRESETS[name].__doc__ or "").strip().splitlines()
            print(f"{name:<12} {doc[0].strip() if doc else ''}")
        return 0
    if not args.preset:
        print("Error: preset name required (or --list)", file=sys.stderr)
        return 1
    universe = make_preset(args.preset, universes=args.universes,
                           seed=args.seed)

    # Explicitly requested axes are validated against the entrypoint's
    # static metric superset (frontier.ENTRYPOINT_METRICS) BEFORE the
    # sweep runs — a typo must not cost a multi-minute batched
    # program.  Only the DEFAULT axes may fall back silently when a
    # preset doesn't define them.
    from consul_tpu.sweep.frontier import ENTRYPOINT_METRICS

    known = ENTRYPOINT_METRICS[universe.entrypoint]
    for requested in (args.frontier_x, args.frontier_y):
        if requested and requested not in known:
            print(
                f"Error: unknown frontier metric {requested!r} for "
                f"{universe.entrypoint!r} sweeps "
                f"(have: {', '.join(sorted(known))})",
                file=sys.stderr,
            )
            return 1

    # Sweep x shard composition: --devices builds the nodes mesh and
    # every generation/sweep program vmaps over the SHARDED inner
    # study.  Entrypoints without a sharded twin reject loudly BEFORE
    # any program runs (same pre-run contract as the axis typos).
    mesh = None
    if args.exchange != "alltoall" and args.devices is None:
        print("Error: --exchange requires --devices (the outbox "
              "transport only exists on the composed plane)",
              file=sys.stderr)
        return 1
    if args.devices is not None:
        from consul_tpu.sweep.universe import SWEEP_ENTRYPOINTS

        if SWEEP_ENTRYPOINTS[universe.entrypoint].sharded is None:
            composable = sorted(
                n for n, s in SWEEP_ENTRYPOINTS.items() if s.sharded
            )
            print(
                f"Error: entrypoint {universe.entrypoint!r} has no "
                f"sharded twin — --devices composes: "
                f"{', '.join(composable)}",
                file=sys.stderr,
            )
            return 1
        from consul_tpu.parallel.mesh import mesh_for

        try:
            mesh = mesh_for(args.devices)
        except ValueError as e:
            print(f"Error: {e}", file=sys.stderr)
            return 1

    if not args.optimize:
        # Optimizer-only flags without --optimize would silently run
        # the full fixed grid — the exact silent-flag failure the
        # pre-run typo contract exists to prevent.
        stray = [flag for flag, hit in (
            ("--objective", bool(args.objective)),
            ("--minimize", args.minimize),
            ("--knee-at", args.knee_at is not None),
            ("--points-per-gen", args.points_per_gen is not None),
            ("--max-generations", args.max_generations != 12),
        ) if hit]
        if stray:
            print(f"Error: {', '.join(stray)} require(s) --optimize",
                  file=sys.stderr)
            return 1

    if args.optimize:
        # Closed loop: the preset's ladders define the search space;
        # the driver finds the optimum/knee in a few batched
        # generations (consul_tpu/sweep/optimize.py).
        if not args.objective:
            print("Error: --optimize requires --objective "
                  f"(metrics for {universe.entrypoint!r}: "
                  f"{', '.join(sorted(known))})", file=sys.stderr)
            return 1
        from consul_tpu.sweep.optimize import optimize_sweep

        try:
            result = optimize_sweep(
                universe, args.objective,
                minimize=args.minimize, knee_at=args.knee_at,
                points_per_gen=args.points_per_gen,
                max_generations=args.max_generations,
                mesh=mesh, exchange=args.exchange,
            )
        except ValueError as e:
            print(f"Error: {e}", file=sys.stderr)
            return 1
        out = result.summary()
        if mesh is not None:
            out["devices"] = args.devices
            out["exchange"] = args.exchange
        print(json.dumps(out, indent=2, default=str))
        return 0

    from consul_tpu.sim.engine import run_sweep

    # No warmup run: the CLI's deliverable is the study summary, not a
    # steady-state timing number (bench.py pays the warm second call
    # where universes_per_sec is the metric) — don't silently double
    # the wall-clock of a multi-minute sweep.
    report = run_sweep(universe, warmup=False, mesh=mesh,
                       exchange=args.exchange)
    out = report.summary()
    import numpy as np

    def _defined(name):
        return name in report.metrics and not np.all(
            np.isnan(np.asarray(report.metrics[name], np.float64))
        )

    fx = args.frontier_x or (
        "false_dead_mean" if _defined("false_dead_mean") else ""
    )
    fy = args.frontier_y or (
        "detect_t90_ms" if _defined("detect_t90_ms")
        else "first_suspect_ms"
    )
    if fx and _defined(fx) and _defined(fy):
        out["frontier"] = report.frontier(x=fx, y=fy)
        out["frontier_axes"] = [fx, fy]
    elif args.frontier_x or args.frontier_y:
        # An EXPLICIT axis request is never silently dropped: say which
        # half of the pair this study failed to provide.  _defined
        # catches both an absent key and an emitted-but-all-NaN metric
        # (e.g. false_dead_mean when the subject crashes at tick 0) —
        # either would otherwise read as "no Pareto points".
        bad = next((m for m in (fx, fy) if m and not _defined(m)), None)
        what = (
            f"metric {bad!r} is not defined for this study"
            if bad else
            "no robustness axis is defined for this study "
            "(pass --frontier-x)"
        )
        have = [m for m in sorted(report.metrics) if _defined(m)]
        print(
            f"Error: cannot build the requested frontier: {what} "
            f"(defined: {', '.join(have)})",
            file=sys.stderr,
        )
        return 1
    print(json.dumps(out, indent=2, default=str))
    return 0


async def cmd_version(args) -> int:
    print(f"consul-tpu v{__version__}")
    return 0


def _print_table(rows: list[tuple]) -> None:
    widths = [max(len(str(r[i])) for r in rows) for i in range(len(rows[0]))]
    for row in rows:
        print("  ".join(str(v).ljust(w) for v, w in zip(row, widths)).rstrip())


def _json_bytes(obj):
    if isinstance(obj, bytes):
        return obj.decode(errors="replace")
    return str(obj)


if __name__ == "__main__":
    sys.exit(main())
