"""The scan engine: whole studies compiled as single XLA programs.

``lax.scan`` over ticks, per-node arrays optionally sharded over a device
mesh (consul_tpu.parallel).  Each scan carries compact per-tick counters
out (infection counts), so a million-node, thousand-tick study transfers
only O(ticks) scalars back to the host.

Round-key derivation is COUNTER-BASED: round ``t`` draws from
``fold_in(scan_key, t)`` (not ``split(key, steps)``, whose keys depend
on the step count), the round functions split that into per-site keys,
and every node-indexed draw folds the global node id in
(ops/sampling.py owned streams) — so trajectories are prefix-stable in
``steps`` and the sharded twins generate draws for their owned n/D
block only while staying bit-equal at D == 1.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.models.broadcast import (
    BroadcastConfig,
    broadcast_init,
    broadcast_round,
)
from consul_tpu.models.membership import (
    RANK_DEAD,
    RANK_SUSPECT,
    MembershipConfig,
    key_rank,
    membership_init,
    membership_round,
)
from consul_tpu.models.multidc import (
    MultiDCConfig,
    multidc_init,
    multidc_round,
)
from consul_tpu.models.swim import (
    SwimConfig,
    swim_init,
    swim_round,
    VIEW_DEAD,
    VIEW_SUSPECT,
)
from consul_tpu.obs.spec import emit_metrics, metric_names
from consul_tpu.parallel import make_mesh, shard_state
from consul_tpu.parallel.shard import (
    sharded_broadcast_scan,
    sharded_geo_scan,
    sharded_membership_scan,
    sharded_sparse_membership_scan,
    sharded_streamcast_scan,
)
from consul_tpu.sim.metrics import (
    BroadcastReport,
    FalsePositiveReport,
    SwimReport,
)


def _broadcast_scan(state, key: jax.Array, cfg: BroadcastConfig, steps: int,
                    telemetry: bool = False):
    """Run ``steps`` gossip ticks; returns (final_state, infected[steps]).

    Unjitted impl: the public :data:`broadcast_scan` wraps it with cfg
    and steps static; the universe-sweep plane (consul_tpu/sweep) vmaps
    it with traced knob fields inside cfg, which a static jit argument
    could never carry (tracers don't hash).  Same split for every scan
    entrypoint below.

    ``telemetry`` (positional-static, like every flag here) appends one
    EXTRA output: the [steps, M] Consul-named metrics trace
    (consul_tpu/obs/spec.py).  Carries, key derivations, and the
    existing trace streams are untouched — telemetry=off is the exact
    current program and telemetry=on is bit-equal on every existing
    output (pinned by tests/test_obs.py; same contract on every scan
    below)."""

    def tick(carry, t):
        nxt = broadcast_round(carry, jax.random.fold_in(key, t), cfg)
        out = jnp.sum(nxt.knows, dtype=jnp.int32)
        if telemetry:
            out = (out, emit_metrics("broadcast", carry, nxt, out, cfg))
        return nxt, out

    return jax.lax.scan(tick, state, jnp.arange(steps, dtype=jnp.int32))


broadcast_scan = jax.jit(
    _broadcast_scan, static_argnames=("cfg", "steps", "telemetry")
)


@functools.partial(jax.jit, static_argnames=("cfg", "steps"))
def multidc_scan(state, key: jax.Array, cfg: MultiDCConfig, steps: int):
    """Run ``steps`` LAN ticks of the two-edge-class broadcast; returns
    (final_state, (infected_total[steps], infected_per_segment[steps, S]))."""

    def tick(carry, t):
        nxt = multidc_round(carry, jax.random.fold_in(key, t), cfg)
        per_seg = jnp.sum(
            nxt.knows.reshape(cfg.segments, cfg.seg_size),
            axis=1,
            dtype=jnp.int32,
        )
        return nxt, (jnp.sum(nxt.knows, dtype=jnp.int32), per_seg)

    return jax.lax.scan(tick, state, jnp.arange(steps, dtype=jnp.int32))


def _swim_scan(state, key: jax.Array, cfg: SwimConfig, steps: int,
               telemetry: bool = False):
    """Run ``steps`` ticks; returns (final_state, (suspecting, dead_known)).
    Unjitted impl of :data:`swim_scan` (see :func:`_broadcast_scan`)."""

    def tick(carry, t):
        nxt = swim_round(carry, jax.random.fold_in(key, t), cfg)
        out = (
            jnp.sum(nxt.view == VIEW_SUSPECT, dtype=jnp.int32),
            jnp.sum(nxt.view == VIEW_DEAD, dtype=jnp.int32),
        )
        if telemetry:
            out = (*out, emit_metrics("swim", carry, nxt, out, cfg))
        return nxt, out

    return jax.lax.scan(tick, state, jnp.arange(steps, dtype=jnp.int32))


swim_scan = jax.jit(
    _swim_scan, static_argnames=("cfg", "steps", "telemetry")
)


def _lifeguard_scan(state, key: jax.Array, cfg, steps: int,
                    telemetry: bool = False):
    """Run ``steps`` fault-injected ticks of the Lifeguard model;
    returns (final_state, (suspecting, dead_known, fp_events, refutes,
    mean_awareness)).

    The false-positive counter is a carry-vs-next diff inside the scan
    body (fresh ALIVE->SUSPECT transitions while the subject is
    actually alive), so the accuracy metrics ride the same O(ticks)
    host transfer as the counts — one jit trace for the whole study.
    """
    # Imported at call time: models.lifeguard depends on sim.faults, so
    # a module-level import here would close an import cycle through
    # the package __init__s.
    from consul_tpu.models.lifeguard import lifeguard_round

    def tick(carry, t):
        nxt = lifeguard_round(carry, jax.random.fold_in(key, t), cfg)
        newly_suspect = jnp.sum(
            (nxt.view == VIEW_SUSPECT) & (carry.view != VIEW_SUSPECT),
            dtype=jnp.int32,
        )
        subject_live = jnp.logical_or(
            jnp.bool_(cfg.subject_alive), carry.tick < cfg.fail_at_tick
        )
        out = (
            jnp.sum(nxt.view == VIEW_SUSPECT, dtype=jnp.int32),
            jnp.sum(nxt.view == VIEW_DEAD, dtype=jnp.int32),
            jnp.where(subject_live, newly_suspect, 0),
            (nxt.subject_inc - carry.subject_inc).astype(jnp.int32),
            jnp.mean(nxt.awareness.astype(jnp.float32)),
        )
        if telemetry:
            out = (*out, emit_metrics("lifeguard", carry, nxt, out, cfg))
        return nxt, out

    return jax.lax.scan(tick, state, jnp.arange(steps, dtype=jnp.int32))


lifeguard_scan = jax.jit(
    _lifeguard_scan, static_argnames=("cfg", "steps", "telemetry")
)


def _membership_scan(state, key: jax.Array, cfg: MembershipConfig, steps: int,
                     track: tuple = (), telemetry: bool = False):
    """Run ``steps`` ticks of the full-membership sim.

    Per tick, for each tracked subject j: how many OTHER nodes view j
    SUSPECT / DEAD; plus the global count of suspect cells (the
    false-positive pressure gauge) and the mean membership-list size
    (join/leave convergence).

    ``state`` is donated (jaxlint J3): the four [n, n] planes dominate
    the dense model's footprint, and donating the initial carry lets
    XLA write the final state into the same buffers — callers pass a
    freshly built state positionally and never reuse it after the
    call (the kw/positional jit-cache convention is unchanged).
    """
    track_idx = jnp.asarray(track, jnp.int32) if track else jnp.zeros(
        (0,), jnp.int32
    )

    def tick(carry, t):
        nxt = membership_round(carry, jax.random.fold_in(key, t), cfg)
        ranks = key_rank(nxt.key)
        cols = ranks[:, track_idx] if track else jnp.zeros(
            (cfg.n, 0), jnp.int32
        )
        out = (
            jnp.sum(cols == RANK_SUSPECT, axis=0, dtype=jnp.int32),
            jnp.sum(cols == RANK_DEAD, axis=0, dtype=jnp.int32),
            jnp.sum(ranks == RANK_SUSPECT, dtype=jnp.int32),
            jnp.sum((nxt.key >= 0) & (ranks <= RANK_SUSPECT), dtype=jnp.int32),
        )
        if telemetry:
            out = (*out, emit_metrics("membership", carry, nxt, out, cfg))
        return nxt, out

    return jax.lax.scan(tick, state, jnp.arange(steps, dtype=jnp.int32))


membership_scan = jax.jit(
    _membership_scan, static_argnames=("cfg", "steps", "track", "telemetry"),
    donate_argnums=(0,),
)


def _timed(make_state, scan_fn, key, cfg, steps, warmup: bool):
    """Run a scan, returning (host outputs, wall seconds).

    The barrier is an explicit device->host transfer of the per-tick
    counters: on some platforms (the axon TPU tunnel) block_until_ready
    returns before execution finishes, so np.asarray is the only honest
    fence.  With ``warmup`` the program is compiled and executed once
    outside the timed region, so the wall time is steady-state.
    """
    if warmup:
        _, out = scan_fn(make_state(), key, cfg, steps)
        jax.tree_util.tree_map(np.asarray, out)
    t0 = time.perf_counter()
    final, out = scan_fn(make_state(), key, cfg, steps)
    out = jax.tree_util.tree_map(np.asarray, out)
    wall = time.perf_counter() - t0
    return final, out, wall


def _check_exchange(exchange: str, mesh, sharded: bool = False) -> None:
    """The exchange backend is a multichip-plane knob: asking for a
    non-default transport without a mesh (or on the legacy GSPMD
    ``sharded=True`` path, which has no outbox) would silently ignore
    it, so reject it loudly instead."""
    if exchange != "alltoall" and (mesh is None or sharded):
        raise ValueError(
            f"exchange={exchange!r} requires mesh= without sharded= "
            "(the outbox transport only exists on the explicit "
            "multi-chip plane)"
        )


def _trace_fields(entrypoint: str, trace) -> dict:
    """Report kwargs of a telemetry=True study (empty when off)."""
    if trace is None:
        return {}
    return {
        "metric_names": metric_names(entrypoint),
        "metrics_trace": np.asarray(trace),
    }


def run_broadcast(
    cfg: BroadcastConfig,
    steps: int,
    seed: int = 0,
    origin: int = 0,
    sharded: bool = False,
    mesh=None,
    warmup: bool = True,
    exchange: str = "alltoall",
    telemetry: bool = False,
) -> BroadcastReport:
    """``mesh=`` alone selects the explicit multi-chip plane
    (consul_tpu/parallel/shard.py: per-device node blocks, outbox
    message routing, D == 1 bit-equal to the unsharded scan) and fills
    ``report.overflow``; ``sharded=True`` keeps the legacy GSPMD
    placement path (shard_state over the unsharded program).
    ``exchange`` picks the outbox transport (``"alltoall"`` |
    ``"ring"``, bit-equal; see parallel/shard.py:exchange_outbox).
    ``telemetry`` fills ``report.metrics_trace`` with the [steps, M]
    Consul-named trace (consul_tpu/obs) — every existing output stays
    bit-equal; same seam on every run_* below."""
    _check_exchange(exchange, mesh, sharded)

    def make_state():
        st = broadcast_init(cfg, origin=origin)
        return shard_state(st, mesh or make_mesh()) if sharded else st

    key = jax.random.PRNGKey(seed)
    if mesh is not None and not sharded:
        # Positional static args on purpose: jit caches keyword and
        # positional call shapes separately, and tests/benches call the
        # sharded scans positionally.
        def scan(st, k, c, s):
            return sharded_broadcast_scan(
                st, k, c, s, mesh, exchange, telemetry
            )

        _, outs, wall = _timed(
            make_state, scan, key, cfg, steps, warmup
        )
        if telemetry:
            infected, ov, trace = outs
        else:
            (infected, ov), trace = outs, None
        return BroadcastReport(
            n=cfg.n,
            ticks=steps,
            tick_ms=cfg.profile.gossip_interval_ms,
            infected=np.asarray(infected),
            wall_s=wall,
            overflow=int(np.asarray(ov)),
            **_trace_fields("broadcast", trace),
        )
    if telemetry:
        def scan(st, k, c, s):  # positional statics: see above
            return broadcast_scan(st, k, c, s, True)
    else:
        scan = broadcast_scan
    _, outs, wall = _timed(make_state, scan, key, cfg, steps, warmup)
    infected, trace = outs if telemetry else (outs, None)
    return BroadcastReport(
        n=cfg.n,
        ticks=steps,
        tick_ms=cfg.profile.gossip_interval_ms,
        infected=np.asarray(infected),
        wall_s=wall,
        **_trace_fields("broadcast", trace),
    )


def run_multidc(
    cfg: MultiDCConfig,
    steps: int,
    seed: int = 0,
    origin: int = 0,
    sharded: bool = False,
    mesh=None,
    warmup: bool = True,
):
    """Two-edge-class (LAN intra-segment / WAN cross-segment) broadcast
    study; with ``sharded`` each device holds whole segments so only the
    WAN class crosses the mesh."""
    from consul_tpu.sim.metrics import MultiDCReport

    def make_state():
        st = multidc_init(cfg, origin=origin)
        return shard_state(st, mesh or make_mesh()) if sharded else st

    key = jax.random.PRNGKey(seed)
    _, (total, per_seg), wall = _timed(
        make_state, multidc_scan, key, cfg, steps, warmup
    )
    return MultiDCReport(
        n=cfg.n,
        segments=cfg.segments,
        ticks=steps,
        tick_ms=cfg.lan_profile.gossip_interval_ms,
        infected=np.asarray(total),
        per_segment=np.asarray(per_seg),
        wall_s=wall,
    )


def run_membership(
    cfg: MembershipConfig,
    steps: int,
    seed: int = 0,
    track: tuple = (),
    sharded: bool = False,
    mesh=None,
    warmup: bool = True,
    exchange: str = "alltoall",
    telemetry: bool = False,
):
    """Full-membership study; ``track`` selects the subject columns whose
    detection curves come back per tick.  ``mesh=`` alone selects the
    explicit multi-chip plane, ``exchange`` its outbox transport,
    ``telemetry`` the metrics trace (see :func:`run_broadcast`)."""
    from consul_tpu.sim.metrics import MembershipReport

    _check_exchange(exchange, mesh, sharded)

    def make_state():
        st = membership_init(cfg)
        return shard_state(st, mesh or make_mesh()) if sharded else st

    key = jax.random.PRNGKey(seed)
    if mesh is not None and not sharded:
        track_t = tuple(track)

        def scan(st, k, c, s):  # positional statics: see run_broadcast
            return sharded_membership_scan(
                st, k, c, s, mesh, track_t, exchange, telemetry
            )

        _, outs, wall = _timed(
            make_state, scan, key, cfg, steps, warmup
        )
        if telemetry:
            sus, dead, sus_cells, known, ov, trace = outs
        else:
            (sus, dead, sus_cells, known, ov), trace = outs, None
        return MembershipReport(
            n=cfg.n,
            ticks=steps,
            tick_ms=cfg.profile.gossip_interval_ms,
            probe_interval_ms=cfg.profile.probe_interval_ms,
            track=tuple(track),
            suspecting=sus,
            dead_known=dead,
            suspect_cells=sus_cells,
            known_members=known,
            wall_s=wall,
            overflow=int(np.asarray(ov)),
            **_trace_fields("membership", trace),
        )
    # Positional statics throughout (tracelint R9): jit caches kw and
    # positional binding styles separately, so a keyword-bound partial
    # here would mint a second program per entrypoint alongside the
    # positional call sites (registry traces, tests, benches).
    track_t = tuple(track)
    if telemetry:
        def scan(st, k, c, s):
            return membership_scan(st, k, c, s, track_t, True)
    else:
        def scan(st, k, c, s):
            return membership_scan(st, k, c, s, track_t)
    _, outs, wall = _timed(
        make_state, scan, key, cfg, steps, warmup
    )
    if telemetry:
        sus, dead, sus_cells, known, trace = outs
    else:
        (sus, dead, sus_cells, known), trace = outs, None
    return MembershipReport(
        n=cfg.n,
        ticks=steps,
        tick_ms=cfg.profile.gossip_interval_ms,
        probe_interval_ms=cfg.profile.probe_interval_ms,
        track=tuple(track),
        suspecting=sus,
        dead_known=dead,
        suspect_cells=sus_cells,
        known_members=known,
        wall_s=wall,
        **_trace_fields("membership", trace),
    )


def _sparse_membership_scan(state, key: jax.Array, cfg, steps: int,
                            track: tuple = (), telemetry: bool = False):
    """Sparse-model twin of :func:`membership_scan`: per tracked subject
    j, how many observers hold a SUSPECT / DEAD slot for j, plus the
    global suspect-slot count and mean known-membership size.

    The per-tick delivery rides the sort-merge kernel
    (ops/sortmerge.py), which permutes slot columns as it allocates —
    every per-slot reduction here is deliberately position-free
    (subject-id matching), so the counters are invariant to the row
    order the sorted-row invariant imposes.

    ``state`` is donated (jaxlint J3): the five [n, K] slot planes are
    ~1.3 GB at the 1M-node config, and donation lets XLA reuse them
    for the output state — same caller contract as
    :func:`membership_scan`."""
    from consul_tpu.models.membership_sparse import sparse_membership_round
    from consul_tpu.models.membership import RANK_SUSPECT as _SUS
    from consul_tpu.models.membership import RANK_DEAD as _DEAD

    track_idx = jnp.asarray(track, jnp.int32) if track else jnp.zeros(
        (0,), jnp.int32
    )

    def tick(carry, t):
        nxt = sparse_membership_round(
            carry, jax.random.fold_in(key, t), cfg
        )
        ranks = key_rank(nxt.key)
        if track:
            # [n, K] slots vs tracked ids → per-subject observer counts.
            hit = nxt.slot_subj[:, :, None] == track_idx[None, None, :]
            sus_t = jnp.sum(
                hit & (ranks == _SUS)[:, :, None], axis=(0, 1),
                dtype=jnp.int32,
            )
            dead_t = jnp.sum(
                hit & (ranks == _DEAD)[:, :, None], axis=(0, 1),
                dtype=jnp.int32,
            )
        else:
            sus_t = jnp.zeros((0,), jnp.int32)
            dead_t = jnp.zeros((0,), jnp.int32)
        occupied = nxt.slot_subj >= 0
        dead_cells = jnp.sum(
            occupied & (ranks > _SUS), dtype=jnp.float32
        )
        out = (
            sus_t,
            dead_t,
            jnp.sum(occupied & (ranks == _SUS), dtype=jnp.int32),
            # Absent slots default to known-alive; n² overflows int32 at
            # the scales this model exists for, so the membership-size
            # sum rides float32 (a gauge, not an exact count).
            jnp.float32(cfg.base.n) * cfg.base.n - dead_cells,
        )
        if telemetry:
            out = (*out, emit_metrics("sparse", carry, nxt, out, cfg))
        return nxt, out

    return jax.lax.scan(tick, state, jnp.arange(steps, dtype=jnp.int32))


sparse_membership_scan = jax.jit(
    _sparse_membership_scan,
    static_argnames=("cfg", "steps", "track", "telemetry"),
    donate_argnums=(0,),
)


def run_membership_sparse(
    cfg,
    steps: int,
    seed: int = 0,
    track: tuple = (),
    warmup: bool = True,
    mesh=None,
    exchange: str = "alltoall",
    telemetry: bool = False,
):
    """Top-K sparse membership study (models/membership_sparse.py): the
    n ≥ 10⁵ regime the dense model's O(N²) state cannot reach, delivered
    through the O(A log K) sort-merge kernel (ops/sortmerge.py).

    ``mesh=`` shards the observer rows over the device mesh
    (consul_tpu/parallel/shard.py); the returned overflow then also
    counts outbox budget misses.  ``exchange`` picks the outbox
    transport (see :func:`run_broadcast`)."""
    from consul_tpu.models.membership_sparse import sparse_membership_init
    from consul_tpu.sim.metrics import MembershipReport

    _check_exchange(exchange, mesh)
    key = jax.random.PRNGKey(seed)
    if mesh is not None:
        track_t = tuple(track)

        def scan(st, k, c, s):  # positional statics: see run_broadcast
            return sharded_sparse_membership_scan(
                st, k, c, s, mesh, track_t, exchange, telemetry
            )
    elif telemetry:
        def scan(st, k, c, s, _t=tuple(track)):
            return sparse_membership_scan(st, k, c, s, _t, True)
    else:
        # Positional statics (tracelint R9; see run_membership).
        def scan(st, k, c, s, _t=tuple(track)):
            return sparse_membership_scan(st, k, c, s, _t)
    final, outs, wall = _timed(
        lambda: sparse_membership_init(cfg), scan, key, cfg, steps, warmup
    )
    if telemetry:
        sus, dead, sus_cells, known, trace = outs
    else:
        (sus, dead, sus_cells, known), trace = outs, None
    report = MembershipReport(
        n=cfg.base.n,
        ticks=steps,
        tick_ms=cfg.base.profile.gossip_interval_ms,
        probe_interval_ms=cfg.base.profile.probe_interval_ms,
        track=tuple(track),
        suspecting=sus,
        dead_known=dead,
        suspect_cells=sus_cells,
        known_members=known,
        wall_s=wall,
        **_trace_fields("sparse", trace),
    )
    return report, int(np.asarray(final.overflow))


def run_lifeguard(
    cfg,
    steps: int,
    seed: int = 0,
    sharded: bool = False,
    mesh=None,
    warmup: bool = True,
    telemetry: bool = False,
) -> FalsePositiveReport:
    """Fault-injected Lifeguard study (cfg: LifeguardConfig): the
    accuracy (FP-rate) workload.  Same single-scan/one-trace contract
    as :func:`run_swim`."""
    from consul_tpu.models.lifeguard import lifeguard_init

    def make_state():
        st = lifeguard_init(cfg)
        return shard_state(st, mesh or make_mesh()) if sharded else st

    key = jax.random.PRNGKey(seed)
    if telemetry:
        def scan(st, k, c, s):  # positional statics: see run_broadcast
            return lifeguard_scan(st, k, c, s, True)
    else:
        scan = lifeguard_scan
    _, outs, wall = _timed(
        make_state, scan, key, cfg, steps, warmup
    )
    if telemetry:
        sus, dead, fp, refutes, aware, trace = outs
    else:
        (sus, dead, fp, refutes, aware), trace = outs, None
    return FalsePositiveReport(
        n=cfg.n,
        ticks=steps,
        tick_ms=cfg.profile.gossip_interval_ms,
        probe_interval_ms=cfg.profile.probe_interval_ms,
        lifeguard=cfg.lifeguard,
        subject_alive=cfg.subject_alive,
        fail_at_tick=cfg.fail_at_tick,
        suspecting=np.asarray(sus),
        dead_known=np.asarray(dead),
        fp_events=np.asarray(fp),
        refutes=np.asarray(refutes),
        mean_awareness=np.asarray(aware),
        wall_s=wall,
        **_trace_fields("lifeguard", trace),
    )


def run_sweep(universe, warmup: bool = True, telemetry: bool = False,
              mesh=None, exchange: str = "alltoall"):
    """Run a universe sweep (consul_tpu/sweep): ONE jitted program
    advances all U universes — stacked carries, per-universe PRNG keys,
    knob values as vmapped [U] arrays — and the stacked per-tick
    counters reduce host-side into a SweepReport (FP rate, flaps,
    detection-latency quantiles, Pareto frontier).

    ``mesh=`` composes the universe axis with the ``nodes`` mesh: the
    U-universe vmap wraps the SHARDED scan twin, so one program holds
    U universes x n/D nodes per device (make_sweep's composition
    seam); the report gains ``outbox_overflow`` — the per-universe
    loud overflow column — and U=1 x D=1 stays bit-equal to the
    unsharded sweep.  ``exchange`` picks the outbox transport.

    The sweep program is cached per (entrypoint, U, telemetry, mesh,
    exchange) — all positional-static, like every engine entrypoint —
    so repeated calls with new seeds or knob VALUES never retrace.
    The stacked carry is donated (same J3 rationale as
    membership_scan: at U x state it dominates the footprint).  U=1
    is bit-equal to the unbatched entrypoint.
    """
    # Lazy: sweep imports this module's unjitted scan impls.
    from consul_tpu.sweep.frontier import summarize_sweep
    from consul_tpu.sweep.universe import make_sweep, stacked_init

    sweep = make_sweep(universe.entrypoint, universe.U, telemetry,
                       mesh, exchange)
    keys = universe.keys()
    values = universe.knob_arrays()

    def call():
        return sweep(
            stacked_init(universe), keys, values, universe.cfg,
            universe.steps, universe.knobs, universe.track,
        )

    if warmup:
        out_w = call()
        jax.tree_util.tree_map(np.asarray, out_w[1])
    t0 = time.perf_counter()
    if mesh is None:
        _final, outs = call()
        overflow = None
    else:
        _final, outs, overflow = call()
        overflow = np.asarray(overflow)
    outs = jax.tree_util.tree_map(np.asarray, outs)
    wall = time.perf_counter() - t0
    trace = None
    if telemetry:
        # The batched [U, steps, M] trace rides as the LAST output of
        # every telemetry=on scan; strip it before the per-model
        # summarizer (whose tuple shapes are the telemetry=off ones).
        *core, trace = outs
        outs = tuple(core)
        if universe.entrypoint == "broadcast":
            outs = outs[0]  # unbatched broadcast out is a bare array
    report = summarize_sweep(universe, outs, wall)
    if trace is not None:
        report.metric_names = metric_names(universe.entrypoint)
        report.metrics_trace = np.asarray(trace)
    if overflow is not None:
        report.outbox_overflow = overflow
        report.devices = int(mesh.devices.size)
    return report


def _streamcast_scan(state, key: jax.Array, cfg, steps: int,
                     telemetry: bool = False):
    """Run ``steps`` ticks of the pipelined event stream
    (consul_tpu/streamcast); returns ``(final_state, outs)`` with
    ``outs`` the per-tick window snapshots + cumulative counters
    (model.streamcast_round docstring).  Unjitted impl of
    :data:`streamcast_scan` (see :func:`_broadcast_scan`); the arrival
    schedule derives from a salted fold-in of ``key``, so per-round
    keys stay bit-identical to ``broadcast_scan``'s and the sweep
    plane gets per-universe schedules for free.
    """
    # Imported at call time: streamcast.model depends on sim.faults,
    # so a module-level import here would close an import cycle
    # through the package __init__s (the models.lifeguard pattern).
    from consul_tpu.streamcast.model import (
        _SCHED_SALT,
        arrival_arrays,
        streamcast_round,
    )

    sched = arrival_arrays(cfg, jax.random.fold_in(key, _SCHED_SALT))

    def tick(carry, t):
        nxt, out = streamcast_round(
            carry, jax.random.fold_in(key, t), cfg, sched
        )
        if telemetry:
            out = (*out, emit_metrics("streamcast", carry, nxt, out, cfg))
        return nxt, out

    return jax.lax.scan(tick, state, jnp.arange(steps, dtype=jnp.int32))


streamcast_scan = jax.jit(
    _streamcast_scan, static_argnames=("cfg", "steps", "telemetry"),
    donate_argnums=(0,),
)


def run_streamcast(
    cfg,
    steps: int,
    seed: int = 0,
    warmup: bool = True,
    mesh=None,
    exchange: str = "alltoall",
    telemetry: bool = False,
    policy: str = None,
):
    """Sustained-load streamcast study (cfg: StreamcastConfig): the
    heavy-traffic workload — a continuous chunked event stream under
    the pipelined per-round transmit budget, with per-event delivery
    tracked in the in-flight window.  Returns a
    :class:`consul_tpu.streamcast.StreamcastReport`.

    ``policy=`` overrides the config's chunk-selection policy
    (streamcast.model.POLICIES — validated by the config rebuild, so a
    typo fails loudly before tracing); the policy is trace-time static
    and lands one jit-cache entry per value, exactly like the config
    field it replaces.  ``mesh=`` shards the chunk planes over the
    device mesh (parallel/shard.py; events ride the per-destination
    outbox seam) and fills ``report.shard_overflow``; ``exchange``
    picks the outbox transport (see :func:`run_broadcast`).  ``state``
    is donated on both paths (jaxlint J3): callers pass a fresh init
    positionally.
    """
    from consul_tpu.streamcast.model import streamcast_init
    from consul_tpu.streamcast.report import StreamcastReport

    if policy is not None and policy != cfg.policy:
        cfg = dataclasses.replace(cfg, policy=policy)
    _check_exchange(exchange, mesh)
    key = jax.random.PRNGKey(seed)
    if mesh is not None:
        def scan(st, k, c, s):  # positional statics: see run_broadcast
            return sharded_streamcast_scan(
                st, k, c, s, mesh, exchange, telemetry
            )
    elif telemetry:
        def scan(st, k, c, s):  # positional statics: see run_broadcast
            return streamcast_scan(st, k, c, s, True)
    else:
        scan = streamcast_scan
    final, outs, wall = _timed(
        lambda: streamcast_init(cfg), scan, key, cfg, steps, warmup
    )
    if telemetry:
        *outs, trace = outs
    else:
        trace = None
    if mesh is not None:
        *outs, shard_ov = outs
        shard_ov = int(np.asarray(shard_ov)[-1])
    else:
        shard_ov = None
    (slot_event, slot_birth, done_count, offered, delivered,
     quiesced, overflow, coalesced, sent) = outs
    return StreamcastReport(
        n=cfg.n,
        ticks=steps,
        tick_ms=cfg.profile.gossip_interval_ms,
        window=cfg.window,
        chunks=cfg.chunks,
        k_events=cfg.k_events,
        slot_event=np.asarray(slot_event),
        slot_birth=np.asarray(slot_birth),
        done_count=np.asarray(done_count),
        offered=np.asarray(offered),
        delivered=np.asarray(delivered),
        quiesced=np.asarray(quiesced),
        window_overflow=np.asarray(overflow),
        coalesced=np.asarray(coalesced),
        sent=np.asarray(sent),
        wall_s=wall,
        policy=cfg.policy,
        shard_overflow=shard_ov,
        **_trace_fields("streamcast", trace),
    )


def _geo_scan(state, key: jax.Array, cfg, steps: int,
              telemetry: bool = False):
    """Run ``steps`` LAN ticks of the geo/WAN plane
    (consul_tpu/geo.model.geo_round); returns ``(final_state, outs)``
    with ``outs`` the per-tick ``(per_segment, offered, admitted,
    queued, overflow, wasted)`` link-accounting counters.  Unjitted
    impl of :data:`geo_scan` (see :func:`_broadcast_scan`)."""
    # Imported at call time: geo.model depends on sim.faults, so a
    # module-level import here would close an import cycle through
    # the package __init__s (the models.lifeguard pattern).
    from consul_tpu.geo.model import geo_round

    def tick(carry, t):
        nxt, out = geo_round(carry, jax.random.fold_in(key, t), cfg)
        if telemetry:
            out = (*out, emit_metrics("geo", carry, nxt, out, cfg))
        return nxt, out

    return jax.lax.scan(tick, state, jnp.arange(steps, dtype=jnp.int32))


geo_scan = jax.jit(
    _geo_scan, static_argnames=("cfg", "steps", "telemetry"),
    donate_argnums=(0,),
)


def run_geo(
    cfg,
    steps: int,
    seed: int = 0,
    warmup: bool = True,
    mesh=None,
    exchange: str = "alltoall",
    telemetry: bool = False,
):
    """Geo-distributed WAN study (cfg: GeoConfig): E concurrent events
    spread over S segments through latency-delayed, bandwidth-capped
    WAN links with adaptive (or fixed) anti-entropy between the bridge
    sets.  Returns a :class:`consul_tpu.geo.GeoReport` with per-segment
    convergence times and the per-link transfer census.

    ``mesh=`` shards the per-node planes over the device mesh with
    segments laid out contiguously (parallel/shard.py: LAN traffic
    stays device-local, only WAN units ride the outbox seam) and fills
    ``report.shard_overflow``; ``exchange`` picks the outbox transport
    (see :func:`run_broadcast`).  ``state`` is donated on both paths
    (jaxlint J3): callers pass a fresh init positionally.
    """
    from consul_tpu.geo.model import geo_init
    from consul_tpu.geo.report import GeoReport

    _check_exchange(exchange, mesh)
    key = jax.random.PRNGKey(seed)
    if mesh is not None:
        def scan(st, k, c, s):  # positional statics: see run_broadcast
            return sharded_geo_scan(
                st, k, c, s, mesh, exchange, telemetry
            )
    elif telemetry:
        def scan(st, k, c, s):  # positional statics: see run_broadcast
            return geo_scan(st, k, c, s, True)
    else:
        scan = geo_scan
    _final, outs, wall = _timed(
        lambda: geo_init(cfg), scan, key, cfg, steps, warmup
    )
    if telemetry:
        *outs, trace = outs
    else:
        trace = None
    if mesh is not None:
        *outs, shard_ov = outs
        shard_ov = int(np.asarray(shard_ov)[-1])
    else:
        shard_ov = None
    per_segment, offered, admitted, queued, overflow, wasted = outs
    return GeoReport(
        n=cfg.n,
        segments=cfg.segments,
        events=cfg.events,
        ticks=steps,
        tick_ms=cfg.lan_profile.gossip_interval_ms,
        msg_bytes=cfg.wan_msg_bytes,
        adaptive=cfg.adaptive,
        per_segment=np.asarray(per_segment),
        offered=np.asarray(offered),
        admitted=np.asarray(admitted),
        queued=np.asarray(queued),
        overflow=np.asarray(overflow),
        wasted=np.asarray(wasted),
        wall_s=wall,
        shard_overflow=shard_ov,
        **_trace_fields("geo", trace),
    )


def run_swim(
    cfg: SwimConfig,
    steps: int,
    seed: int = 0,
    sharded: bool = False,
    mesh=None,
    warmup: bool = True,
    telemetry: bool = False,
) -> SwimReport:
    def make_state():
        st = swim_init(cfg)
        return shard_state(st, mesh or make_mesh()) if sharded else st

    key = jax.random.PRNGKey(seed)
    if telemetry:
        def scan(st, k, c, s):  # positional statics: see run_broadcast
            return swim_scan(st, k, c, s, True)
    else:
        scan = swim_scan
    _, outs, wall = _timed(make_state, scan, key, cfg, steps, warmup)
    if telemetry:
        sus, dead, trace = outs
    else:
        (sus, dead), trace = outs, None
    return SwimReport(
        n=cfg.n,
        ticks=steps,
        tick_ms=cfg.profile.gossip_interval_ms,
        probe_interval_ms=cfg.profile.probe_interval_ms,
        suspecting=np.asarray(sus),
        dead_known=np.asarray(dead),
        wall_s=wall,
        **_trace_fields("swim", trace),
    )


# ---------------------------------------------------------------------------
# jaxlint entrypoint registry: name -> traced-program spec.
#
# Every jitted study entrypoint above, at two canonical abstract
# configurations: "small" (the shapes the unit tests pin) and "big"
# (the 1M-node north-star configs bench.py runs).  The specs carry NO
# device arrays — state pytrees come from jax.eval_shape over the
# model inits, so registering/tracing the 1M configs allocates nothing
# (consul_tpu/analysis/jaxlint.py walks the traced jaxprs).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SimProgram:
    """One registered simulation program for jaxpr-level analysis.

    ``build()`` returns ``(fn, args)`` where ``fn`` closes over the
    static configuration and ``args`` are abstract
    ``ShapeDtypeStruct`` pytrees; :meth:`trace` turns it into the
    ``ClosedJaxpr`` the rule engine walks.  ``per_chip`` marks sharded
    programs whose J6 footprint is read from the shard_map body
    (block shapes = per-device bytes); ``x64`` traces under
    ``jax.experimental.enable_x64`` (fixture escape hatch — the real
    registry never sets it)."""

    name: str
    entrypoint: str
    build: Callable[[], tuple[Callable, tuple]]
    n: int
    devices: int = 1
    per_chip: bool = False
    budgeted: bool = True
    x64: bool = False
    note: str = ""
    # Abstract-only entries exist for eval_shape/make_jaxpr gates
    # (J6 capacity, rangelint ledgers) at populations that must never
    # be compiled or executed; profile_registry skips them LOUDLY.
    abstract_only: bool = False
    # rangelint metadata (consul_tpu/analysis/rangelint.py): ``bounds``
    # returns a pytree CONGRUENT with build()'s args whose leaves are
    # rangelint ``Bound`` instances — the initial-value interval of
    # every input plane, derived from the config (node ids, ticks,
    # budgets).  ``scale`` rebuilds the same entrypoint at population
    # n' (the 10M-node narrowing-ledger hook).
    bounds: Optional[Callable[[], Any]] = None
    scale: Optional[Callable[[int], "SimProgram"]] = None
    # equivlint witness seam (consul_tpu/analysis/equivlint.py):
    # ``init`` rebuilds the CONCRETE initial state (the same callable
    # build() eval_shapes), so a declared EQUIV_PAIR the canonicalizer
    # cannot close gets its one tiny-shape witness execution as
    # ``fn(init(), PRNGKey(0))``.  None for programs whose args are not
    # (state, key)-shaped (the sweep plane carries its own builders).
    init: Optional[Callable[[], Any]] = None

    def trace(self) -> Any:
        fn, args = self.build()
        if self.x64:
            from jax.experimental import enable_x64

            with enable_x64():
                return jax.make_jaxpr(fn)(*args)
        return jax.make_jaxpr(fn)(*args)


def _abstract_key() -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct((2,), jnp.uint32)


# ---------------------------------------------------------------------------
# rangelint bound metadata: initial-value intervals per input plane,
# derived from the config.  The abstract interpreter widens these to a
# scan-carry fixpoint, so bounds describe the INIT (what the program is
# handed), not the steady state (what rangelint proves).
# ---------------------------------------------------------------------------


def _broadcast_bounds(cfg: BroadcastConfig):
    def make():
        from consul_tpu.analysis.rangelint import Bound
        from consul_tpu.models.broadcast import BroadcastState

        return (BroadcastState(
            knows=Bound(0, 1),
            tx_left=Bound(0, cfg.tx_limit),
            tick=Bound(0, 0),
        ), Bound.any())

    return make


def _membership_bounds(cfg: MembershipConfig):
    def make():
        from consul_tpu.analysis.rangelint import Bound
        from consul_tpu.models.membership import NEVER, MembershipState

        nv = int(NEVER)
        return (MembershipState(
            key=Bound(-1, 0),
            suspect_since=Bound(nv, nv),
            confirms=Bound(0, 0),
            tx=Bound(0, 0),
            own_inc=Bound(0, 0),
            awareness=Bound(0, 0),
            probe_pending_at=Bound(nv, nv),
            probe_subject=Bound(0, 0),
            tick=Bound(0, 0),
        ), Bound.any())

    return make


def _sparse_bounds(cfg):
    def make():
        from consul_tpu.analysis.rangelint import Bound
        from consul_tpu.models.membership import NEVER
        from consul_tpu.models.membership_sparse import (
            AGE_NONE,
            SparseMembershipState,
        )

        nv = int(NEVER)
        n = cfg.base.n
        return (SparseMembershipState(
            slot_subj=Bound(-1, n - 1),
            key=Bound(0, 0),
            # Age-packed timer plane: -1 sentinel, saturates at
            # AGE_CAP (the int16 certificate rides this bound).
            suspect_since=Bound(AGE_NONE, AGE_NONE),
            confirms=Bound(0, 0),
            tx=Bound(0, 0),
            own_inc=Bound(0, 0),
            awareness=Bound(0, 0),
            probe_pending_at=Bound(nv, nv),
            probe_subject=Bound(0, 0),
            overflow=Bound(0, 0),
            forgotten=Bound(0, 0),
            tick=Bound(0, 0),
        ), Bound.any())

    return make


def _swim_bounds(cfg: SwimConfig):
    def make():
        from consul_tpu.analysis.rangelint import Bound
        from consul_tpu.models.swim import NEVER, SwimState

        nv = int(NEVER)
        z = Bound(0, 0)
        return (SwimState(
            view=z, inc_seen=z,
            suspect_since=Bound(nv, nv),
            confirmations=z, tx_suspect=z, sus_era=z, tx_dead=z,
            dead_era=z, tx_refute=z, ref_era=z,
            probe_pending_at=Bound(nv, nv),
            awareness=z, subject_inc=z, tick=z,
        ), Bound.any())

    return make


def _geo_bounds(cfg):
    def make():
        from consul_tpu.analysis.rangelint import Bound
        from consul_tpu.geo.model import GeoState

        return (GeoState(
            knows=Bound(0, 1),
            tx_lan=Bound(0, cfg.tx_limit_lan),
            ring=Bound(0, 0),
            queue=Bound(0, 0),
            known_hist=Bound(0, 1),
            ewma=Bound.any(),
            wasted=Bound(0, 0),
            tick=Bound(0, 0),
        ), Bound.any())

    return make


def _streamcast_bounds(cfg):
    def make():
        from consul_tpu.analysis.rangelint import Bound
        from consul_tpu.streamcast.model import StreamcastState

        z = Bound(0, 0)
        return (StreamcastState(
            chunks=Bound(0, 1),
            tx_left=z,
            # Chunk cursor: stores (sel + 1) % E (pipeline) or the
            # uncapped sel + 1 (rarest's cycle-spent park), so the
            # int8/int16 narrowing certificate is [0, E].
            cursor=z,
            slot_event=Bound(-1, -1),
            slot_birth=z,
            offered=z, delivered=z, quiesced=z,
            window_overflow=z, coalesced=z, tick=z,
        ), Bound.any())

    return make


def _lifeguard_bounds(cfg):
    def make():
        from consul_tpu.analysis.rangelint import Bound
        from consul_tpu.models.swim import NEVER, SwimState

        nv = int(NEVER)
        z = Bound(0, 0)
        return (SwimState(
            view=z, inc_seen=z,
            suspect_since=Bound(nv, nv),
            confirmations=z, tx_suspect=z, sus_era=z, tx_dead=z,
            dead_era=z, tx_refute=z, ref_era=z,
            probe_pending_at=Bound(nv, nv),
            awareness=z, subject_inc=z, tick=z,
        ), Bound.any())

    return make


def _multidc_bounds(cfg):
    def make():
        from consul_tpu.analysis.rangelint import Bound
        from consul_tpu.models.multidc import MultiDCState

        return (MultiDCState(
            knows=Bound(0, 1),
            tx_lan=Bound(0, cfg.tx_limit_lan),
            tx_wan=Bound(0, cfg.tx_limit_wan),
            tick=Bound(0, 0),
        ), Bound.any())

    return make


def sparse_program_at(n: int, steps: int = 3,
                      track: tuple = (42,)) -> SimProgram:
    """The sparse membership entrypoint at population ``n`` — the
    registry's ``scale`` hook, so rangelint's narrowing ledger reads
    the certificate table against 10M nodes, not just the declared
    configs.  Same K/loss/profile/fault shape as the big registry
    entry; tracing stays abstract (eval_shape + make_jaxpr)."""
    from consul_tpu.models.membership_sparse import (
        SparseMembershipConfig,
        sparse_membership_init,
    )
    from consul_tpu.protocol import LAN

    cfg = SparseMembershipConfig(
        base=MembershipConfig(n=n, loss=0.01, profile=LAN,
                              fail_at=((42, 5),)),
        k_slots=64,
    )

    def build():
        state = jax.eval_shape(lambda: sparse_membership_init(cfg))
        return (
            lambda s, k: sparse_membership_scan(s, k, cfg, steps, track),
            (state, _abstract_key()),
        )

    return SimProgram(
        name=f"sparse@n={n}", entrypoint="sparse_membership_scan",
        build=build, n=n, bounds=_sparse_bounds(cfg),
    )


def swim_program_at(n: int, steps: int = 450) -> SimProgram:
    """The swim entrypoint at population ``n`` (scale hook twin of
    :func:`sparse_program_at`)."""
    from consul_tpu.protocol import WAN

    cfg = SwimConfig(n=n, subject=42, loss=0.30, profile=WAN,
                     delivery="aggregate")

    def build():
        state = jax.eval_shape(lambda: swim_init(cfg))
        return (
            lambda s, k: swim_scan(s, k, cfg, steps),
            (state, _abstract_key()),
        )

    return SimProgram(
        name=f"swim@n={n}", entrypoint="swim_scan", build=build, n=n,
        bounds=_swim_bounds(cfg),
    )


def broadcast_program_at(n: int, steps: int = 60) -> SimProgram:
    """The broadcast entrypoint at population ``n`` (scale hook)."""
    from consul_tpu.protocol import LAN

    cfg = BroadcastConfig(n=n, fanout=4, profile=LAN,
                          delivery="aggregate")

    def build():
        state = jax.eval_shape(lambda: broadcast_init(cfg))
        return (
            lambda s, k: broadcast_scan(s, k, cfg, steps),
            (state, _abstract_key()),
        )

    return SimProgram(
        name=f"broadcast@n={n}", entrypoint="broadcast_scan",
        build=build, n=n, bounds=_broadcast_bounds(cfg),
    )


def jaxlint_registry(include=("small", "big"),
                     sharded_devices=(1, 2)) -> dict[str, SimProgram]:
    """The jaxlint registry: dense/sparse/broadcast scans, their
    sharded twins at D in ``sharded_devices``, the lifeguard scan, and
    the swim/multidc companions, at small-n and 1M-node configs.

    Sharded entries needing more devices than the process exposes are
    skipped (the test harness and ``cli jaxlint`` force 8 virtual CPU
    devices; a bare single-device process still lints the unsharded
    plane).  The dense membership entries register at n=16384 — the
    [n, n] representation's practical per-chip ceiling; n >= 1e5 is
    exactly the regime the sparse model exists for.
    """
    from consul_tpu.models.lifeguard import LifeguardConfig, lifeguard_init
    from consul_tpu.models.membership_sparse import (
        SparseMembershipConfig,
        sparse_membership_init,
    )
    from consul_tpu.parallel import make_mesh
    from consul_tpu.protocol import LAN, WAN

    programs: dict[str, SimProgram] = {}

    def add(name: str, entrypoint: str, init, scan_call, n: int,
            devices: int = 1, **kw) -> None:
        if devices > len(jax.devices()):
            return

        def build(init=init, scan_call=scan_call):
            state = jax.eval_shape(init)
            return scan_call, (state, _abstract_key())

        programs[name] = SimProgram(
            name=name, entrypoint=entrypoint, build=build, n=n,
            devices=devices, init=init, **kw,
        )

    def add_sharded(tag: str, d: int, bcfg, bsteps, mcfg, msteps, mtrack,
                    scfg, ssteps, strack,
                    exchanges: tuple = ("alltoall",)) -> None:
        if d > len(jax.devices()):
            return
        mesh = make_mesh(jax.devices()[:d])
        for ex in exchanges:
            # The alltoall entries keep their historical names; the
            # ring twins (the Pallas make_async_remote_copy kernel,
            # ops/ring_exchange.py) get a /ring suffix so jaxlint's
            # zero-findings gates walk the pallas_call program too.
            sfx = "" if ex == "alltoall" else f"/{ex}"
            add(f"sharded_broadcast@{tag}/D{d}{sfx}",
                "sharded_broadcast_scan",
                lambda: broadcast_init(bcfg),
                lambda s, k, ex=ex: sharded_broadcast_scan(
                    s, k, bcfg, bsteps, mesh, ex),
                bcfg.n, devices=d, per_chip=True,
                bounds=_broadcast_bounds(bcfg))
            add(f"sharded_membership@{tag}/D{d}{sfx}",
                "sharded_membership_scan",
                lambda: membership_init(mcfg),
                lambda s, k, ex=ex: sharded_membership_scan(
                    s, k, mcfg, msteps, mesh, mtrack, ex),
                mcfg.n, devices=d, per_chip=True,
                bounds=_membership_bounds(mcfg))
            add(f"sharded_sparse@{tag}/D{d}{sfx}",
                "sharded_sparse_membership_scan",
                lambda: sparse_membership_init(scfg),
                lambda s, k, ex=ex: sharded_sparse_membership_scan(
                    s, k, scfg, ssteps, mesh, strack, ex),
                scfg.base.n, devices=d, per_chip=True,
                bounds=_sparse_bounds(scfg))

    from consul_tpu.streamcast.model import (
        StreamcastConfig,
        streamcast_init,
    )

    def add_sharded_streamcast(tag: str, d: int, stcfg, ststeps: int,
                               exchanges: tuple = ("alltoall",)) -> None:
        if d > len(jax.devices()):
            return
        mesh = make_mesh(jax.devices()[:d])
        for ex in exchanges:
            sfx = "" if ex == "alltoall" else f"/{ex}"
            add(f"sharded_streamcast@{tag}/D{d}{sfx}",
                "sharded_streamcast_scan",
                lambda: streamcast_init(stcfg),
                lambda s, k, ex=ex: sharded_streamcast_scan(
                    s, k, stcfg, ststeps, mesh, ex),
                stcfg.n, devices=d, per_chip=True,
                bounds=_streamcast_bounds(stcfg))

    from consul_tpu.geo.model import GeoConfig, geo_init

    def add_sharded_geo(tag: str, d: int, gcfg, gsteps: int,
                        exchanges: tuple = ("alltoall",)) -> None:
        if d > len(jax.devices()):
            return
        mesh = make_mesh(jax.devices()[:d])
        for ex in exchanges:
            sfx = "" if ex == "alltoall" else f"/{ex}"
            add(f"sharded_geo@{tag}/D{d}{sfx}",
                "sharded_geo_scan",
                lambda: geo_init(gcfg),
                lambda s, k, ex=ex: sharded_geo_scan(
                    s, k, gcfg, gsteps, mesh, ex),
                gcfg.n, devices=d, per_chip=True,
                bounds=_geo_bounds(gcfg))

    if "small" in include:
        mcfg = MembershipConfig(n=48, loss=0.05, fail_at=((3, 2),))
        bcfg = BroadcastConfig(n=64, fanout=3, delivery="edges")
        scfg = SparseMembershipConfig(base=mcfg, k_slots=8)
        swcfg = SwimConfig(n=64, subject=1, loss=0.05)
        lgcfg = LifeguardConfig(n=64, subject=1, subject_alive=True)
        mdcfg = MultiDCConfig(n=64, segments=8)
        stcfg = StreamcastConfig(n=64, events=12, chunks=2, window=4,
                                 fanout=3, chunk_budget=2, rate=0.4,
                                 names=3, loss=0.05, delivery="edges")
        add("broadcast@small", "broadcast_scan",
            lambda: broadcast_init(bcfg),
            lambda s, k: broadcast_scan(s, k, bcfg, 8), bcfg.n,
            bounds=_broadcast_bounds(bcfg))
        add("membership@small", "membership_scan",
            lambda: membership_init(mcfg),
            lambda s, k: membership_scan(s, k, mcfg, 8, (3,)), mcfg.n,
            bounds=_membership_bounds(mcfg))
        add("sparse@small", "sparse_membership_scan",
            lambda: sparse_membership_init(scfg),
            lambda s, k: sparse_membership_scan(s, k, scfg, 8, (3,)),
            mcfg.n, bounds=_sparse_bounds(scfg))
        add("swim@small", "swim_scan",
            lambda: swim_init(swcfg),
            lambda s, k: swim_scan(s, k, swcfg, 8), swcfg.n,
            bounds=_swim_bounds(swcfg))
        add("lifeguard@small", "lifeguard_scan",
            lambda: lifeguard_init(lgcfg),
            lambda s, k: lifeguard_scan(s, k, lgcfg, 8), lgcfg.n,
            bounds=_lifeguard_bounds(lgcfg))
        add("multidc@small", "multidc_scan",
            lambda: multidc_init(mdcfg),
            lambda s, k: multidc_scan(s, k, mdcfg, 8), mdcfg.n,
            bounds=_multidc_bounds(mdcfg))
        add("streamcast@small", "streamcast_scan",
            lambda: streamcast_init(stcfg),
            lambda s, k: streamcast_scan(s, k, stcfg, 8), stcfg.n,
            bounds=_streamcast_bounds(stcfg))
        # Selection-policy twins: the policy is trace-time static, so
        # each non-uniform policy is a DISTINCT program (the pipeline
        # twin carries the int8 cursor arithmetic rangelint certifies)
        # — both under every zero-findings gate, unsharded + sharded.
        for pol in ("pipeline", "rarest"):
            stcfg_p = dataclasses.replace(stcfg, policy=pol)
            add(f"streamcast@small/{pol}", "streamcast_scan",
                lambda c=stcfg_p: streamcast_init(c),
                lambda s, k, c=stcfg_p: streamcast_scan(s, k, c, 8),
                stcfg.n, bounds=_streamcast_bounds(stcfg_p))
            for d in sharded_devices:
                add_sharded_streamcast(f"small/{pol}", d, stcfg_p, 8)
        # Explicit-default twins: the SAME program spelled with its
        # defaults written out — policy="uniform" explicit, telemetry
        # False explicit, sparse amortize auto resolved to its value.
        # These are the PROVED rungs of the exactness ladder
        # (EQUIV_PAIRS below): equivlint closes each by canonical-
        # jaxpr identity, zero executions, so "a preset is just a
        # point in knob space" stays machine-checked as the knob
        # surface grows (ROADMAP item 1).
        stcfg_u = dataclasses.replace(stcfg, policy="uniform")
        add("streamcast@small/uniform", "streamcast_scan",
            lambda: streamcast_init(stcfg_u),
            lambda s, k: streamcast_scan(s, k, stcfg_u, 8), stcfg.n,
            bounds=_streamcast_bounds(stcfg_u))
        add("broadcast@small/notelemetry", "broadcast_scan",
            lambda: broadcast_init(bcfg),
            lambda s, k: broadcast_scan(s, k, bcfg, 8, False), bcfg.n,
            bounds=_broadcast_bounds(bcfg))
        from consul_tpu.models.membership_sparse import resolve_amortize

        scfg_am = dataclasses.replace(
            scfg, amortize=resolve_amortize(scfg)
        )
        add("sparse@small/amortize", "sparse_membership_scan",
            lambda: sparse_membership_init(scfg_am),
            lambda s, k: sparse_membership_scan(s, k, scfg_am, 8, (3,)),
            mcfg.n, bounds=_sparse_bounds(scfg_am))
        # Adversarial-load twin (sim/load.py): standing backlog +
        # heavy-tailed sizes + hotspot origins — the born-delivered
        # chunk-mask and backlog-pinning paths under the gates.
        stcfg_adv = dataclasses.replace(
            stcfg, backlog=4, size_tail=1.0, hotspot=0.5,
            policy="pipeline",
        )
        add("streamcast@small/adversarial", "streamcast_scan",
            lambda: streamcast_init(stcfg_adv),
            lambda s, k: streamcast_scan(s, k, stcfg_adv, 8),
            stcfg.n, bounds=_streamcast_bounds(stcfg_adv))
        gecfg = GeoConfig(n=64, segments=8, bridges_per_segment=2,
                          events=4, wan_window=4, wan_msg_bytes=100,
                          wan_capacity_bytes=800.0,
                          wan_queue_bytes=1600.0, ae_batch=4,
                          loss_wan=0.05)
        add("geo@small", "geo_scan",
            lambda: geo_init(gecfg),
            lambda s, k: geo_scan(s, k, gecfg, 8), gecfg.n,
            bounds=_geo_bounds(gecfg))
        for d in sharded_devices:
            add_sharded_geo("small", d, gecfg, 8,
                            exchanges=("alltoall", "ring"))
        for d in sharded_devices:
            add_sharded_streamcast("small", d, stcfg, 8,
                                   exchanges=("alltoall", "ring"))
        for d in sharded_devices:
            # Both exchange backends at small-n: the ring twins put the
            # Pallas ring kernel's traced program under every jaxlint
            # gate (the big set stays alltoall-only — the 1M ring
            # programs are identical modulo the pallas_call eqn, and
            # big traces cost ~5 s each).
            add_sharded("small", d, bcfg, 8, mcfg, 8, (3,),
                        scfg, 8, (3,), exchanges=("alltoall", "ring"))
        # telemetry=on twins (consul_tpu/obs): every zero-findings gate
        # walks the metrics-emission path of all seven entrypoints —
        # and of the five sharded twins' psum assembly (alltoall only:
        # the emission is transport-independent).
        add("broadcast@small/telemetry", "broadcast_scan",
            lambda: broadcast_init(bcfg),
            lambda s, k: broadcast_scan(s, k, bcfg, 8, True), bcfg.n,
            bounds=_broadcast_bounds(bcfg))
        add("membership@small/telemetry", "membership_scan",
            lambda: membership_init(mcfg),
            lambda s, k: membership_scan(s, k, mcfg, 8, (3,), True),
            mcfg.n, bounds=_membership_bounds(mcfg))
        add("sparse@small/telemetry", "sparse_membership_scan",
            lambda: sparse_membership_init(scfg),
            lambda s, k: sparse_membership_scan(
                s, k, scfg, 8, (3,), True),
            mcfg.n, bounds=_sparse_bounds(scfg))
        add("swim@small/telemetry", "swim_scan",
            lambda: swim_init(swcfg),
            lambda s, k: swim_scan(s, k, swcfg, 8, True), swcfg.n,
            bounds=_swim_bounds(swcfg))
        add("lifeguard@small/telemetry", "lifeguard_scan",
            lambda: lifeguard_init(lgcfg),
            lambda s, k: lifeguard_scan(s, k, lgcfg, 8, True), lgcfg.n,
            bounds=_lifeguard_bounds(lgcfg))
        add("streamcast@small/telemetry", "streamcast_scan",
            lambda: streamcast_init(stcfg),
            lambda s, k: streamcast_scan(s, k, stcfg, 8, True), stcfg.n,
            bounds=_streamcast_bounds(stcfg))
        add("geo@small/telemetry", "geo_scan",
            lambda: geo_init(gecfg),
            lambda s, k: geo_scan(s, k, gecfg, 8, True), gecfg.n,
            bounds=_geo_bounds(gecfg))
        for d in sharded_devices:
            if d > len(jax.devices()):
                continue
            mesh_t = make_mesh(jax.devices()[:d])
            add(f"sharded_broadcast@small/D{d}/telemetry",
                "sharded_broadcast_scan",
                lambda: broadcast_init(bcfg),
                lambda s, k, m=mesh_t: sharded_broadcast_scan(
                    s, k, bcfg, 8, m, "alltoall", True),
                bcfg.n, devices=d, per_chip=True,
                bounds=_broadcast_bounds(bcfg))
            add(f"sharded_membership@small/D{d}/telemetry",
                "sharded_membership_scan",
                lambda: membership_init(mcfg),
                lambda s, k, m=mesh_t: sharded_membership_scan(
                    s, k, mcfg, 8, m, (3,), "alltoall", True),
                mcfg.n, devices=d, per_chip=True,
                bounds=_membership_bounds(mcfg))
            add(f"sharded_sparse@small/D{d}/telemetry",
                "sharded_sparse_membership_scan",
                lambda: sparse_membership_init(scfg),
                lambda s, k, m=mesh_t: sharded_sparse_membership_scan(
                    s, k, scfg, 8, m, (3,), "alltoall", True),
                scfg.base.n, devices=d, per_chip=True,
                bounds=_sparse_bounds(scfg))
            add(f"sharded_streamcast@small/D{d}/telemetry",
                "sharded_streamcast_scan",
                lambda: streamcast_init(stcfg),
                lambda s, k, m=mesh_t: sharded_streamcast_scan(
                    s, k, stcfg, 8, m, "alltoall", True),
                stcfg.n, devices=d, per_chip=True,
                bounds=_streamcast_bounds(stcfg))
            add(f"sharded_geo@small/D{d}/telemetry",
                "sharded_geo_scan",
                lambda: geo_init(gecfg),
                lambda s, k, m=mesh_t: sharded_geo_scan(
                    s, k, gecfg, 8, m, "alltoall", True),
                gecfg.n, devices=d, per_chip=True,
                bounds=_geo_bounds(gecfg))
    if "big" in include:
        # The north-star shapes bench.py measures: 1M nodes for the
        # per-node-plane models (dense membership capped at its 16k
        # [n, n] per-chip ceiling), and the sharded twins at 1M nodes
        # PER CHIP (n = 1M x D, edges delivery — the multichip bench
        # config) at the largest registered mesh.
        mcfg1m = MembershipConfig(n=16384, loss=0.01, profile=LAN,
                                  fail_at=((42, 5),))
        bcfg1m = BroadcastConfig(n=1_000_000, fanout=4, profile=LAN,
                                 delivery="aggregate")
        scfg1m = SparseMembershipConfig(
            base=MembershipConfig(n=1_000_000, loss=0.01, profile=LAN,
                                  fail_at=((42, 5),)),
            k_slots=64,
        )
        swcfg1m = SwimConfig(n=1_000_000, subject=42, loss=0.30,
                             profile=WAN, delivery="aggregate")
        lgcfg1m = LifeguardConfig(n=1_000_000, subject=42,
                                  subject_alive=True, ack_late=0.02,
                                  profile=WAN)
        add("broadcast@1m", "broadcast_scan",
            lambda: broadcast_init(bcfg1m),
            lambda s, k: broadcast_scan(s, k, bcfg1m, 60), bcfg1m.n,
            bounds=_broadcast_bounds(bcfg1m),
            scale=broadcast_program_at)
        add("membership@16k", "membership_scan",
            lambda: membership_init(mcfg1m),
            lambda s, k: membership_scan(s, k, mcfg1m, 30, (42,)),
            mcfg1m.n,
            bounds=_membership_bounds(mcfg1m),
            note="dense [n, n] ceiling: n >= 1e5 belongs to the sparse "
                 "model")
        add("sparse@1m", "sparse_membership_scan",
            lambda: sparse_membership_init(scfg1m),
            lambda s, k: sparse_membership_scan(s, k, scfg1m, 3, (42,)),
            scfg1m.base.n, bounds=_sparse_bounds(scfg1m),
            scale=sparse_program_at)
        # The 10M-node target itself, abstract-only (eval_shape +
        # make_jaxpr — zero device memory): keeps the J6 ≤ 16 GB/chip
        # claim and the rangelint 10M certificate table PINNED by the
        # registry gates instead of re-derived ad hoc.  This is the
        # capacity the PR 12 narrowing + sentinel packing buys.
        scfg10m = SparseMembershipConfig(
            base=MembershipConfig(n=10_000_000, loss=0.01, profile=LAN,
                                  fail_at=((42, 5),)),
            k_slots=64,
        )
        add("sparse@10m", "sparse_membership_scan",
            lambda: sparse_membership_init(scfg10m),
            lambda s, k: sparse_membership_scan(
                s, k, scfg10m, 3, (42,)),
            scfg10m.base.n, bounds=_sparse_bounds(scfg10m),
            scale=sparse_program_at, abstract_only=True,
            note="abstract-only 10M capacity gate (never executed in "
                 "CI; J6 + rangelint read the traced program)")
        add("swim@1m", "swim_scan",
            lambda: swim_init(swcfg1m),
            lambda s, k: swim_scan(s, k, swcfg1m, 450), swcfg1m.n,
            bounds=_swim_bounds(swcfg1m), scale=swim_program_at)
        add("lifeguard@1m", "lifeguard_scan",
            lambda: lifeguard_init(lgcfg1m),
            lambda s, k: lifeguard_scan(s, k, lgcfg1m, 160), lgcfg1m.n,
            bounds=_lifeguard_bounds(lgcfg1m))
        # The sustained-load workload at the north-star scale: 1M nodes,
        # 4-chunk events pipelined through an 8-slot window, Poisson
        # offered load — bench.py's streaming section shapes.
        stcfg1m = StreamcastConfig(n=1_000_000, events=256, chunks=4,
                                   window=8, fanout=4, chunk_budget=2,
                                   rate=0.5, names=32, profile=LAN,
                                   done_frac=0.999,
                                   delivery="aggregate")
        add("streamcast@1m", "streamcast_scan",
            lambda: streamcast_init(stcfg1m),
            lambda s, k: streamcast_scan(s, k, stcfg1m, 150),
            stcfg1m.n, bounds=_streamcast_bounds(stcfg1m))
        # The geo/WAN plane at the north-star scale: 1M nodes over 8
        # DCs, 16 concurrent events, bandwidth-capped Vivaldi-latency
        # links — bench.py's "geo" section shapes.
        gecfg1m = GeoConfig(n=1_000_000, segments=8,
                            bridges_per_segment=5, events=16,
                            wan_window=8, wan_msg_bytes=1400,
                            wan_capacity_bytes=16 * 1400.0,
                            wan_queue_bytes=32 * 1400.0, ae_batch=16,
                            loss_wan=0.05)
        add("geo@1m", "geo_scan",
            lambda: geo_init(gecfg1m),
            lambda s, k: geo_scan(s, k, gecfg1m, 60), gecfg1m.n,
            bounds=_geo_bounds(gecfg1m))
        d = max(
            (d for d in sharded_devices if d <= len(jax.devices())),
            default=0,
        )
        if d:
            add_sharded(
                "1m_per_chip", d,
                BroadcastConfig(n=1_000_000 * d, fanout=4, profile=LAN,
                                delivery="edges"),
                30,
                mcfg1m, 30, (42,),
                SparseMembershipConfig(
                    base=MembershipConfig(n=1_000_000 * d, loss=0.01,
                                          profile=LAN,
                                          fail_at=((42, 5),)),
                    k_slots=64,
                ),
                3, (42,),
            )
            add_sharded_streamcast(
                "1m_per_chip", d,
                StreamcastConfig(n=1_000_000 * d, events=256, chunks=4,
                                 window=8, fanout=4, chunk_budget=2,
                                 rate=0.5, names=32, profile=LAN,
                                 done_frac=0.999,
                                 delivery="edges"),
                10,
            )

    # Universe-sweep twins (consul_tpu/sweep): the vmapped programs at
    # U in {1, 8}, each with a live rate knob so every zero-findings
    # gate walks the traced knob-rebuild path, not just the batching.
    # U is the knob that blows the J6 budget first — the big set pins
    # the batched sparse footprint at 100k nodes so the estimator's
    # ~linear-in-U scaling (and the max-U-per-chip table it implies)
    # stays measured.
    from consul_tpu.sweep.universe import abstract_sweep_program

    def add_sweep(tag: str, model: str, cfg, steps: int, U: int,
                  knobs: tuple, track: tuple, n: int,
                  telemetry: bool = False, d: int = 0) -> None:
        # d > 0 builds the COMPOSED sweep x shard program: the
        # U-universe vmap over the sharded inner study on a d-device
        # mesh (make_sweep(mesh=); skipped when the process lacks the
        # devices, like every sharded entry).
        if d and d > len(jax.devices()):
            return
        mesh = make_mesh(jax.devices()[:d]) if d else None

        def build(model=model, cfg=cfg, steps=steps, U=U, knobs=knobs,
                  track=track, telemetry=telemetry, mesh=mesh):
            return abstract_sweep_program(model, cfg, steps, U, knobs,
                                          track, telemetry, mesh)

        sfx = "/telemetry" if telemetry else ""
        dfx = f"xD{d}" if d else ""
        programs[f"sweep_{model}@{tag}/U{U}{dfx}{sfx}"] = SimProgram(
            name=f"sweep_{model}@{tag}/U{U}{dfx}{sfx}",
            entrypoint="sweep_scan", build=build, n=n,
            devices=d or 1, per_chip=bool(d),
        )

    if "small" in include:
        sw_small = (
            ("swim", SwimConfig(n=64, subject=1, loss=0.05), 8,
             ("loss",), (), 64),
            ("lifeguard", LifeguardConfig(n=64, subject=1,
                                          subject_alive=True), 8,
             ("loss", "ack_late"), (), 64),
            ("broadcast", BroadcastConfig(n=64, fanout=3,
                                          delivery="edges"), 8,
             ("loss",), (), 64),
            ("membership", MembershipConfig(n=48, loss=0.05,
                                            fail_at=((3, 2),)), 8,
             ("loss", "suspicion_scale"), (3,), 48),
            ("sparse", SparseMembershipConfig(
                base=MembershipConfig(n=48, loss=0.05,
                                      fail_at=((3, 2),)),
                k_slots=8), 8,
             ("base.loss",), (3,), 48),
            ("streamcast", StreamcastConfig(
                n=64, events=12, chunks=2, window=4, fanout=3,
                chunk_budget=2, rate=0.4, names=3, loss=0.05,
                delivery="edges"), 8,
             ("rate",), (), 64),
            ("geo", GeoConfig(n=64, segments=8, bridges_per_segment=2,
                              events=4, wan_window=4, wan_msg_bytes=100,
                              wan_capacity_bytes=800.0,
                              wan_queue_bytes=1600.0, ae_batch=4,
                              loss_wan=0.05), 8,
             ("loss_wan",), (), 64),
        )
        for model, cfg, steps, knobs, track, n in sw_small:
            for u in (1, 8):
                add_sweep("small", model, cfg, steps, u, knobs, track, n)
        # Policy twins of the batched streamcast plane: policy is
        # static under the sweep too (one cached program per policy ×
        # U), so the policy × offered-load grid is <= 3 vmapped
        # programs — pinned under the gates at U in {1, 8}.
        st_row = next(r for r in sw_small if r[0] == "streamcast")
        for pol in ("pipeline", "rarest"):
            _, st_cfg, st_steps, st_knobs, st_track, st_n = st_row
            pcfg = dataclasses.replace(st_cfg, policy=pol)
            for u in (1, 8):
                add_sweep(f"small/{pol}", "streamcast", pcfg, st_steps,
                          u, st_knobs, st_track, st_n)
        # Batched telemetry twin: the [U, steps, M] trace plane under
        # the zero-findings gates (one model suffices — the obs seam
        # is shared by every vmapped impl).
        sw_model, sw_cfg, sw_steps, sw_knobs, sw_track, sw_n = sw_small[0]
        add_sweep("small", sw_model, sw_cfg, sw_steps, 8, sw_knobs,
                  sw_track, sw_n, telemetry=True)
        # COMPOSED sweep x shard twins: the five sharded-twin families
        # at U in {1, 8} x D in sharded_devices, so every zero-findings
        # gate walks the vmapped-shard_map program (outbox pack/
        # exchange under the universe batch, per-universe knob rebuild
        # inside the shard body).  J6 pin: the composed footprint is
        # ~U x the per-shard study + the replicated knob/key planes —
        # tests/test_sweepshard.py reads it off these entries.
        for model, cfg, steps, knobs, track, n in sw_small:
            if model in ("swim", "lifeguard"):
                continue  # no sharded twin (rejected loudly by make_sweep)
            for u in (1, 8):
                for d in sharded_devices:
                    add_sweep("small", model, cfg, steps, u, knobs,
                              track, n, d=d)
    if "big" in include:
        scfg100k = SparseMembershipConfig(
            base=MembershipConfig(n=100_000, loss=0.01, profile=LAN,
                                  fail_at=((42, 5),)),
            k_slots=64,
        )
        for u in (1, 8):
            add_sweep("100k", "sparse", scfg100k, 3, u,
                      ("base.loss",), (42,), 100_000)
    return programs


# ---------------------------------------------------------------------------
# EQUIV_PAIRS: the exactness ladder as DATA.
#
# Each rung of the repo's bit-equality ladder — D == 1 is the unsharded
# program, ring == alltoall, U == 1 is the plain scan, telemetry=off is
# the identity, explicit defaults == omitted flags — declared as one
# EquivPair of registry keys + the input relation, certified by
# consul_tpu/analysis/equivlint.py: structural canonical-jaxpr identity
# (PROVED) where the two builds trace to the same program, one shared
# tiny-shape witness execution (WITNESSED) where they are genuinely
# different programs with equal projected outputs.  Runtime bit-
# equality tests for WITNESSED rungs keep one tier-1 representative per
# family; the rest ride `-m slow` (tests/test_shard.py, test_obs.py).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EquivPair:
    """One declared ladder rung: registry keys ``a``/``b`` plus the
    input relation.  ``project_a``/``project_b`` map each side's raw
    output pytree onto the common comparison domain (e.g. drop the
    sharded twin's trailing overflow leaf); ``args_a``/``args_b``
    override the default witness args ``(init(), PRNGKey(0))`` for
    programs with differently-shaped inputs (the sweep plane's
    ``(stacked_state, keys, knob_values)``)."""

    a: str
    b: str
    relation: str
    family: str
    project_a: Optional[Callable[[Any], Any]] = None
    project_b: Optional[Callable[[Any], Any]] = None
    args_a: Optional[Callable[[], tuple]] = None
    args_b: Optional[Callable[[], tuple]] = None
    note: str = ""


def _drop_last_out(out):
    """(final, (outs..., extra)) -> (final, outs) — strips the trailing
    leaf a sharded twin (outbox overflow) or telemetry twin (metrics
    trace) appends to the unsharded/off program's outs tuple."""
    final, outs = out
    return (final, tuple(outs)[:-1])


def _scalar_out(out):
    """(final, (scalar_plane, extra)) -> (final, scalar_plane) — the
    broadcast family's unsharded outs is a bare array, so its twins
    project to element 0 rather than a shorter tuple."""
    final, outs = out
    return (final, outs[0])


def _squeeze_u(out):
    """Drop the leading U=1 universe axis from every leaf — the sweep
    twin's outputs are the plain scan's stacked once."""
    return jax.tree_util.tree_map(lambda x: x[0], out)


def _sweep_u1_args(model: str) -> Callable[[], tuple]:
    """Concrete witness args for a U=1 sweep twin: the plain program's
    init stacked to [1, ...], PRNGKey(0) as the single universe key,
    and the config's OWN value for each knob — exactly the relation the
    U=1 rung claims (sweeping a knob at its default is the plain
    scan)."""

    def make() -> tuple:
        from consul_tpu.sweep.universe import (
            SWEEP_ENTRYPOINTS,
            knob_dtype,
            _resolve_path,
        )

        if model == "swim":
            cfg = SwimConfig(n=64, subject=1, loss=0.05)
            knobs = ("loss",)
        elif model == "broadcast":
            cfg = BroadcastConfig(n=64, fanout=3, delivery="edges")
            knobs = ("loss",)
        else:
            raise ValueError(f"no U=1 witness builder for {model!r}")
        spec = SWEEP_ENTRYPOINTS[model]
        state = spec.init(cfg)
        stacked = jax.tree_util.tree_map(lambda a: a[None], state)
        keys = jax.random.PRNGKey(0)[None]
        values = tuple(
            jnp.full((1,), getattr(*_resolve_path(cfg, p)),
                     knob_dtype(p))
            for p in knobs
        )
        return (stacked, keys, values)

    return make


def _build_equiv_pairs() -> tuple:
    from consul_tpu.parallel.shard import (
        SHARDED_EXTRA_OVERFLOW,
        SHARDED_TWINS,
    )

    pairs = [
        # Explicit-default rungs — same program, different spelling:
        # the canonicalizer closes these structurally (PROVED).
        EquivPair("streamcast@small/uniform", "streamcast@small",
                  relation="flag omitted: policy='uniform' == default",
                  family="streamcast"),
        EquivPair("broadcast@small/notelemetry", "broadcast@small",
                  relation="flag omitted: telemetry=False == default",
                  family="broadcast"),
        EquivPair("sparse@small/amortize", "sparse@small",
                  relation="amortize auto == explicit resolved value",
                  family="sparse"),
    ]
    for sharded, family in sorted(SHARDED_TWINS.items()):
        if sharded == "sharded_broadcast":
            proj = _scalar_out
        elif sharded in SHARDED_EXTRA_OVERFLOW:
            proj = _drop_last_out
        else:
            proj = None  # outputs align 1:1 (sparse)
        pairs.append(EquivPair(
            f"{sharded}@small/D1", f"{family}@small",
            relation="D=1 slice == unsharded", family=family,
            project_a=proj,
        ))
        pairs.append(EquivPair(
            f"{sharded}@small/D2/ring", f"{sharded}@small/D2",
            relation="ring == alltoall (D=2)", family=family,
        ))
    for family, proj in (
        ("broadcast", _scalar_out),
        ("membership", _drop_last_out),
        ("sparse", _drop_last_out),
        ("swim", _drop_last_out),
        ("lifeguard", _drop_last_out),
        ("streamcast", _drop_last_out),
        ("geo", _drop_last_out),
    ):
        pairs.append(EquivPair(
            f"{family}@small/telemetry", f"{family}@small",
            relation="telemetry == off on every existing output",
            family=family, project_a=proj,
        ))
    for model in ("swim", "broadcast"):
        pairs.append(EquivPair(
            f"sweep_{model}@small/U1", f"{model}@small",
            relation="U=1 sweep == plain scan", family=model,
            project_a=_squeeze_u, args_a=_sweep_u1_args(model),
        ))
    return tuple(pairs)


EQUIV_PAIRS: tuple = _build_equiv_pairs()
