"""Convergence metrics computed from per-tick scan traces.

The counter names follow the reference's metric tree style
(lib/telemetry.go; e.g. serf.queue.Event, memberlist.msg.suspect) so the
simulator's output reads like the real agent's telemetry.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


def time_to_fraction(counts: np.ndarray, n: int, frac: float) -> Optional[int]:
    """First tick index at which counts/n >= frac, or None if never."""
    hit = np.nonzero(np.asarray(counts) >= frac * n)[0]
    return int(hit[0]) if hit.size else None


@dataclasses.dataclass
class BroadcastReport:
    """Infection curve summary for one event broadcast."""

    n: int
    ticks: int
    tick_ms: float
    infected: np.ndarray          # int per tick (post-tick counts)
    wall_s: float                 # host wall time for the simulated run
    # Sharded (shard_map) runs only: outbox messages dropped to the
    # static per-shard budget (consul_tpu/parallel/shard.py); 0 means
    # the multi-chip run delivered exactly what a single chip would.
    overflow: Optional[int] = None
    # telemetry=True runs only (consul_tpu/obs): the [steps, M]
    # Consul-named metrics trace and its ordered column names.
    metric_names: tuple = ()
    metrics_trace: Optional[np.ndarray] = None

    def time_to_ms(self, frac: float) -> Optional[float]:
        t = time_to_fraction(self.infected, self.n, frac)
        return None if t is None else (t + 1) * self.tick_ms

    @property
    def rounds_per_sec(self) -> float:
        return self.ticks / self.wall_s if self.wall_s > 0 else float("inf")

    def summary(self) -> dict:
        return {
            "n": self.n,
            "ticks": self.ticks,
            "tick_ms": self.tick_ms,
            "infected_final": int(self.infected[-1]),
            "t50_ms": self.time_to_ms(0.50),
            "t99_ms": self.time_to_ms(0.99),
            "t9999_ms": self.time_to_ms(0.9999),
            "sim_rounds_per_sec": self.rounds_per_sec,
        }


@dataclasses.dataclass
class MultiDCReport:
    """Infection curves for a segmented (multi-DC) broadcast: global and
    per-segment, so the WAN hop's latency contribution is visible."""

    n: int
    segments: int
    ticks: int
    tick_ms: float
    infected: np.ndarray          # int32[ticks] — global
    per_segment: np.ndarray       # int32[ticks, S]
    wall_s: float

    @property
    def rounds_per_sec(self) -> float:
        return self.ticks / self.wall_s if self.wall_s > 0 else float("inf")

    @property
    def seg_size(self) -> int:
        return self.n // self.segments

    def time_to_ms(self, frac: float) -> Optional[float]:
        t = time_to_fraction(self.infected, self.n, frac)
        return None if t is None else (t + 1) * self.tick_ms

    def segment_t99_ms(self, s: int) -> Optional[float]:
        t = time_to_fraction(self.per_segment[:, s], self.seg_size, 0.99)
        return None if t is None else (t + 1) * self.tick_ms

    def segments_reached(self) -> int:
        """Segments with at least one infected member at the end."""
        return int((self.per_segment[-1] > 0).sum())

    def summary(self) -> dict:
        return {
            "n": self.n,
            "segments": self.segments,
            "ticks": self.ticks,
            "tick_ms": self.tick_ms,
            "infected_final": int(self.infected[-1]),
            "segments_reached": self.segments_reached(),
            "t50_ms": self.time_to_ms(0.50),
            "t99_ms": self.time_to_ms(0.99),
            "segment_t99_ms": [
                self.segment_t99_ms(s) for s in range(self.segments)
            ],
            "sim_rounds_per_sec": self.rounds_per_sec,
        }


@dataclasses.dataclass
class MembershipReport:
    """Detection curves from a full-membership study (one column per
    tracked subject)."""

    n: int
    ticks: int
    tick_ms: float
    probe_interval_ms: float
    track: tuple                  # tracked subject ids
    suspecting: np.ndarray        # int32[ticks, S] — observers suspecting j
    dead_known: np.ndarray        # int32[ticks, S]
    suspect_cells: np.ndarray     # int32[ticks] — global suspicion pressure
    known_members: np.ndarray     # int32[ticks] — sum of membership sizes
    wall_s: float
    # Sharded (shard_map) runs only — see BroadcastReport.overflow.
    overflow: Optional[int] = None
    # telemetry=True runs only (consul_tpu/obs): the [steps, M]
    # Consul-named metrics trace and its ordered column names.
    metric_names: tuple = ()
    metrics_trace: Optional[np.ndarray] = None

    @property
    def rounds_per_sec(self) -> float:
        return self.ticks / self.wall_s if self.wall_s > 0 else float("inf")

    def first_tick(self, counts: np.ndarray) -> Optional[int]:
        hit = np.nonzero(np.asarray(counts) > 0)[0]
        return int(hit[0]) if hit.size else None

    def first_detection_ms(self, subject_pos: int) -> Optional[float]:
        """First tick any observer suspects tracked subject #pos."""
        t = self.first_tick(self.suspecting[:, subject_pos])
        return None if t is None else (t + 1) * self.tick_ms

    def dead_converged(self, subject_pos: int, observers: int) -> Optional[int]:
        """First tick when every live observer views the subject DEAD."""
        hit = np.nonzero(self.dead_known[:, subject_pos] >= observers)[0]
        return int(hit[0]) if hit.size else None

    def summary(self) -> dict:
        return {
            "n": self.n,
            "ticks": self.ticks,
            "tick_ms": self.tick_ms,
            "tracked": list(self.track),
            "first_suspect_ms": [
                self.first_detection_ms(i) for i in range(len(self.track))
            ],
            "dead_known_final": self.dead_known[-1].tolist(),
            "suspect_cells_final": int(self.suspect_cells[-1]),
            "mean_membership_final": float(self.known_members[-1]) / self.n,
            "sim_rounds_per_sec": self.rounds_per_sec,
        }


@dataclasses.dataclass
class FalsePositiveReport:
    """Accuracy summary of a Lifeguard study: how often does the
    cluster wrongly suspect a live subject, how hard does it flap, and
    what does the accuracy buy/cost in time-to-true-dead?

    All per-tick columns come out of the single-scan trace (O(ticks)
    host transfer):

      suspecting[t]      observers currently viewing the subject SUSPECT
      dead_known[t]      observers currently viewing the subject DEAD
      fp_events[t]       fresh ALIVE->SUSPECT transitions while the
                         subject was actually alive (the false-positive
                         counter; memberlist.msg.suspect in telemetry
                         terms)
      refutes[t]         incarnation bumps by the subject this tick
                         (each is one refute broadcast; their total is
                         the incarnation *flap* count)
      mean_awareness[t]  population-mean Lifeguard health score
    """

    n: int
    ticks: int
    tick_ms: float
    probe_interval_ms: float
    lifeguard: bool
    subject_alive: bool
    fail_at_tick: int
    suspecting: np.ndarray       # int32[ticks]
    dead_known: np.ndarray       # int32[ticks]
    fp_events: np.ndarray        # int32[ticks]
    refutes: np.ndarray          # int32[ticks]
    mean_awareness: np.ndarray   # float32[ticks]
    wall_s: float
    # telemetry=True runs only (consul_tpu/obs): the [steps, M]
    # Consul-named metrics trace and its ordered column names.
    metric_names: tuple = ()
    metrics_trace: Optional[np.ndarray] = None

    @property
    def rounds_per_sec(self) -> float:
        return self.ticks / self.wall_s if self.wall_s > 0 else float("inf")

    @property
    def fp_total(self) -> int:
        """Total false-positive suspicion events over the study."""
        return int(np.sum(self.fp_events))

    @property
    def fp_rate(self) -> float:
        """False-positive suspicions per simulated second (cluster-wide)."""
        sim_s = self.ticks * self.tick_ms / 1000.0
        return self.fp_total / sim_s if sim_s > 0 else 0.0

    @property
    def refute_total(self) -> int:
        return int(np.sum(self.refutes))

    @property
    def flap_count(self) -> int:
        """Incarnation flaps: each refute restarts the cycle one
        incarnation higher (suspect@k -> refute@k+1 -> ...)."""
        return self.refute_total

    def first_tick(self, counts: np.ndarray) -> Optional[int]:
        hit = np.nonzero(np.asarray(counts) > 0)[0]
        return int(hit[0]) if hit.size else None

    def time_to_true_dead_ms(self) -> Optional[float]:
        """Simulated ms from the subject's actual crash to the first
        observer viewing it DEAD (None for FP studies or if never).

        Only ticks at/after the crash count: a false-DEAD view that a
        refute later repairs (the race the model permits under FP
        pressure) must not produce a negative or pre-crash time.
        """
        if self.subject_alive:
            return None
        since_fail = np.asarray(self.dead_known)[self.fail_at_tick:]
        t = self.first_tick(since_fail)
        if t is None:
            return None
        return (t + 1) * self.tick_ms

    def summary(self) -> dict:
        return {
            "n": self.n,
            "ticks": self.ticks,
            "tick_ms": self.tick_ms,
            "lifeguard": self.lifeguard,
            "fp_total": self.fp_total,
            "fp_rate_per_s": round(self.fp_rate, 4),
            "refute_total": self.refute_total,
            "flap_count": self.flap_count,
            "suspecting_final": int(self.suspecting[-1]),
            "dead_known_final": int(self.dead_known[-1]),
            "mean_awareness_final": float(self.mean_awareness[-1]),
            "time_to_true_dead_ms": self.time_to_true_dead_ms(),
            "sim_rounds_per_sec": self.rounds_per_sec,
        }


@dataclasses.dataclass
class SwimReport:
    """Failure-detection summary for one subject."""

    n: int
    ticks: int
    tick_ms: float
    probe_interval_ms: float
    suspecting: np.ndarray        # nodes viewing subject SUSPECT, per tick
    dead_known: np.ndarray        # nodes viewing subject DEAD, per tick
    wall_s: float
    # telemetry=True runs only (consul_tpu/obs): the [steps, M]
    # Consul-named metrics trace and its ordered column names.
    metric_names: tuple = ()
    metrics_trace: Optional[np.ndarray] = None

    @property
    def rounds_per_sec(self) -> float:
        return self.ticks / self.wall_s if self.wall_s > 0 else float("inf")

    def first_tick(self, counts: np.ndarray) -> Optional[int]:
        hit = np.nonzero(np.asarray(counts) > 0)[0]
        return int(hit[0]) if hit.size else None

    def summary(self) -> dict:
        fd = self.first_tick(self.suspecting)
        fdead = self.first_tick(self.dead_known)
        t99 = time_to_fraction(self.dead_known, self.n - 1, 0.99)
        return {
            "n": self.n,
            "ticks": self.ticks,
            "tick_ms": self.tick_ms,
            "first_suspect_ms": None if fd is None else (fd + 1) * self.tick_ms,
            "first_dead_ms": None if fdead is None else (fdead + 1) * self.tick_ms,
            "t99_dead_known_ms": None if t99 is None else (t99 + 1) * self.tick_ms,
            "suspecting_final": int(self.suspecting[-1]),
            "dead_known_final": int(self.dead_known[-1]),
            "sim_rounds_per_sec": self.rounds_per_sec,
        }
