"""The BASELINE.json study configs as runnable presets.

Each scenario returns a summary dict via ``run_scenario(name)`` — the
programmatic entry point for the benchmark harness (and the CLI, once
the host agent plane lands).

  dev3        3-node LAN pool, single user-event broadcast (CPU ref)
  probe1k     1k-node SWIM probe/ack with 1% induced failure, fanout 3
  event100k   100k-node serf event broadcast, LAN timing, fanout 4,
              99% infection time
  stream100k  100k-node sustained event stream (consul_tpu/streamcast):
              Poisson 4-chunk events pipelined through an 8-slot
              window, delivered events/sec + t50/t99 + overflow
  geo100k     100k-node geo/WAN study (consul_tpu/geo): 8 DCs,
              Vivaldi-derived link latencies, a scheduled bandwidth
              brownout, adaptive anti-entropy — per-segment
              convergence + the per-link transfer census
  suspect1m   1M-node suspicion/dead propagation, 30% loss, WAN profile
  multidc1m   1M-node 8-segment multi-DC epidemic broadcast, sharded
              across the device mesh
  degraded1m  1M-node Lifeguard false-positive study, WAN profile, 2%
              degraded members (dropped/late acks) — runs the same
              faulted universe with Lifeguard on and off and reports
              the FP-rate / flap deltas (the first accuracy workload)
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import numpy as np

from consul_tpu.models import BroadcastConfig, MembershipConfig, SwimConfig
from consul_tpu.protocol import LAN, WAN
from consul_tpu.sim.engine import run_broadcast, run_membership, run_swim



def _metrics_out(entrypoint: str, rep) -> dict:
    """Bridge a telemetry=True report into a FRESH telemetry.Metrics
    (not the process-global agent sink) and return the
    /v1/agent/metrics-shaped snapshot for the scenario summary."""
    from consul_tpu.obs import bridge_report
    from consul_tpu.telemetry import Metrics

    return {"metrics": bridge_report(entrypoint, rep, Metrics()).snapshot()}


def dev3(seed: int = 0, telemetry: bool = False) -> dict:
    """BASELINE config 1: 3-node dev pool, one user event (CPU-scale ref).

    The 3-node `agent -dev` LAN pool of the reference; at this size the
    exact edge simulation is the only sensible mode."""
    cfg = BroadcastConfig(n=3, profile=LAN, delivery="edges")
    rep = run_broadcast(cfg, steps=10, seed=seed, warmup=False,
                        telemetry=telemetry)
    return {
        "scenario": "dev3",
        **rep.summary(),
        **(_metrics_out("broadcast", rep) if telemetry else {}),
    }


def probe1k(seed: int = 0, devices: int = None,
            exchange: str = "alltoall", telemetry: bool = False) -> dict:
    """BASELINE config 2: 1k nodes, SWIM probe/ack, 1% induced failure.

    1% of 1000 = 10 CONCURRENT crashes in one full-membership program
    (models/membership.py): the failures interact through shared gossip
    bandwidth, confirmation cross-talk, and the push/pull backstop —
    the dynamics 10 independent single-subject universes can't show.

    ``devices`` shards the observer rows over the first D devices
    (``cli sim probe1k --devices D``); ``exchange`` picks the outbox
    transport (``--exchange ring`` = the Pallas DMA kernel)."""
    from consul_tpu.parallel import mesh_for

    failed = tuple(range(0, 1000, 100))  # 10 spread-out subjects
    cfg = MembershipConfig(
        n=1000, loss=0.0, profile=LAN, fanout=3,
        fail_at=tuple((f, 10) for f in failed),
    )
    rep = run_membership(cfg, steps=300, seed=seed, track=failed,
                         warmup=False,
                         mesh=mesh_for(devices) if devices else None,
                         exchange=exchange, telemetry=telemetry)
    first_sus = [rep.first_detection_ms(i) for i in range(len(failed))]
    live = cfg.n - len(failed)
    conv = [rep.dead_converged(i, live) for i in range(len(failed))]
    return {
        "scenario": "probe1k",
        "n": cfg.n,
        "subjects": len(failed),
        "mean_first_suspect_ms": float(
            np.mean([s for s in first_sus if s])
        ) if any(first_sus) else None,
        "all_detected": all(c is not None for c in conv),
        "mean_converged_ms": float(np.mean(
            [(c + 1) * rep.tick_ms for c in conv if c is not None]
        )) if any(c is not None for c in conv) else None,
        "sim_rounds_per_sec": rep.rounds_per_sec,
        **({"devices": devices, "exchange_backend": exchange,
            "shard_overflow": rep.overflow}
           if devices else {}),
        **(_metrics_out("membership", rep) if telemetry else {}),
    }


def event100k(seed: int = 0, devices: int = None,
              exchange: str = "alltoall",
              telemetry: bool = False) -> dict:
    """BASELINE config 3: 100k-node event broadcast, LAN, fanout 4.

    ``devices`` runs the exact per-message path sharded over the first
    D devices (``cli sim event100k --devices D``) — the outbox plane,
    with budget misses reported as shard_overflow; ``exchange`` picks
    the transport (all_to_all collective or the Pallas ring kernel)."""
    from consul_tpu.parallel import mesh_for

    if devices:
        cfg = BroadcastConfig(n=100_000, fanout=4, profile=LAN,
                              delivery="edges")
        rep = run_broadcast(cfg, steps=100, seed=seed,
                            mesh=mesh_for(devices), exchange=exchange,
                            telemetry=telemetry)
        return {"scenario": "event100k", **rep.summary(),
                "devices": devices, "exchange_backend": exchange,
                "shard_overflow": rep.overflow,
                **(_metrics_out("broadcast", rep) if telemetry else {})}
    cfg = BroadcastConfig(n=100_000, fanout=4, profile=LAN,
                          delivery="aggregate")
    # exchange threads through so a non-default transport without a
    # mesh is rejected by the engine, not silently dropped (same
    # loud-never-silent contract as probe1k).
    rep = run_broadcast(cfg, steps=100, seed=seed, exchange=exchange,
                        telemetry=telemetry)
    return {"scenario": "event100k", **rep.summary(),
            **(_metrics_out("broadcast", rep) if telemetry else {})}


def stream100k(seed: int = 0, n: int = 100_000, steps: int = 150,
               devices: int = None, exchange: str = "alltoall",
               telemetry: bool = False,
               policy: str = "uniform") -> dict:
    """Sustained event stream at 100k nodes: Poisson arrivals of
    4-chunk events pipelined through an 8-slot window under a fixed
    2-slot/round budget (consul_tpu/streamcast) — the heavy-traffic
    workload as a preset, reporting delivered events/sec against the
    offered load with t50/t99 delivery quantiles and the
    window-overflow saturation signal.

    ``policy`` picks the chunk-selection schedule (``cli sim
    stream100k --policy {uniform,pipeline,rarest}``; streamcast.model
    POLICIES — a typo fails loudly at config construction) and is
    echoed in the summary.  ``devices`` shards the chunk planes over
    the first D devices (``cli sim stream100k --devices D``) — chunk
    messages ride the per-destination outbox, budget misses reported
    as shard_overflow; ``exchange`` picks the transport (``--exchange
    ring`` = the Pallas DMA kernel).  ``n``/``steps`` scale down for
    CPU smoke runs."""
    from consul_tpu.parallel import mesh_for
    from consul_tpu.sim.engine import run_streamcast
    from consul_tpu.streamcast import StreamcastConfig

    rate = 0.3
    cfg = StreamcastConfig(
        n=n, events=int(rate * steps * 1.5), chunks=4, window=8,
        fanout=4, chunk_budget=2, rate=rate, names=16, loss=0.05,
        profile=LAN, done_frac=0.999, policy=policy,
        delivery="edges" if devices else "aggregate",
    )
    rep = run_streamcast(cfg, steps=steps, seed=seed, warmup=False,
                         mesh=mesh_for(devices) if devices else None,
                         exchange=exchange, telemetry=telemetry)
    return {
        "scenario": "stream100k",
        **rep.summary(),
        **({"devices": devices, "exchange_backend": exchange}
           if devices else {}),
        **(_metrics_out("streamcast", rep) if telemetry else {}),
    }


def geo100k(seed: int = 0, n: int = 100_000, steps: int = 120,
            devices: int = None, exchange: str = "alltoall",
            telemetry: bool = False) -> dict:
    """100k-node geo/WAN study (consul_tpu/geo): 8 DCs with
    Vivaldi-derived per-link latency, bandwidth-capped WAN links under
    a mid-run brownout, and adaptive anti-entropy between the bridge
    sets — per-segment convergence plus the loud per-link transfer
    census.

    ``devices`` lays the segments contiguously over the first D
    devices (``cli sim geo100k --devices D``: LAN traffic stays
    device-local, WAN units ride the outbox; budget misses reported as
    shard_overflow); ``exchange`` picks the transport (``--exchange
    ring`` = the Pallas DMA kernel).  ``n``/``steps`` scale down for
    CPU smoke runs."""
    from consul_tpu.geo.latency import derive_wan_latency
    from consul_tpu.geo.model import GeoConfig
    from consul_tpu.parallel import mesh_for
    from consul_tpu.sim.engine import run_geo
    from consul_tpu.sim.faults import BandwidthSchedule, FaultSchedule

    base_bytes = 16 * 1400.0
    latency, vinfo = derive_wan_latency(
        8, 3, tick_ms=LAN.gossip_interval_ms, seed=seed, rounds=300,
        wan_window=8,
    )
    cfg = GeoConfig(
        n=n, segments=8, bridges_per_segment=3, events=16,
        wan_latency_ticks=latency, wan_window=8,
        wan_capacity_bytes=base_bytes, wan_msg_bytes=1400,
        wan_queue_bytes=2 * base_bytes, ae_batch=16, adaptive=True,
        loss_wan=0.05,
        faults=FaultSchedule(bandwidth=(
            BandwidthSchedule(pieces=((20, 0.2 * base_bytes),
                                      (80, 64 * base_bytes))),
        )),
    )
    rep = run_geo(cfg, steps=steps, seed=seed, warmup=False,
                  mesh=mesh_for(devices) if devices else None,
                  exchange=exchange, telemetry=telemetry)
    return {
        "scenario": "geo100k",
        **rep.summary(),
        "vivaldi_rel_rtt_error": round(vinfo["rel_rtt_error"], 4),
        **(_metrics_out("geo", rep) if telemetry else {}),
        **({"devices": devices, "exchange_backend": exchange}
           if devices else {}),
    }


def suspect1m(seed: int = 0) -> dict:
    """BASELINE config 4: 1M-node suspicion/dead propagation, 30% loss,
    WAN timing."""
    cfg = SwimConfig(n=1_000_000, subject=42, loss=0.30, profile=WAN,
                     delivery="aggregate")
    # Suspicion min timeout at 1M WAN = 6*log10(1e6)*5s = 180s = 360
    # ticks; run past it so dead propagation is measured.
    rep = run_swim(cfg, steps=500, seed=seed)
    return {"scenario": "suspect1m", **rep.summary()}


def multidc1m(seed: int = 0) -> dict:
    """BASELINE config 5: 1M nodes in 8 segments, TWO edge classes —
    LAN gossip inside each segment, WAN-profile gossip (slower cadence,
    server bridges only, memberlist/config.go:315-326) across segments —
    sharded one segment per device so all LAN traffic is device-local
    and only WAN crosses the mesh."""
    from consul_tpu.models.multidc import MultiDCConfig
    from consul_tpu.parallel import make_mesh
    from consul_tpu.sim.engine import run_multidc

    mesh = make_mesh()
    cfg = MultiDCConfig(
        n=1_000_000,
        segments=8,
        bridges_per_segment=5,
        delivery="aggregate",
    )
    # Origin is a non-bridge node of segment 0: the event must climb
    # onto the WAN through segment 0's servers and re-enter every other
    # segment through theirs (flood.go path in reverse).
    rep = run_multidc(cfg, steps=120, seed=seed, origin=cfg.seg_size // 2,
                      sharded=True, mesh=mesh)
    return {"scenario": "multidc1m", **rep.summary()}


# The degraded1m fault environment, importable so tests pin the SAME
# knobs the scenario ships (2% slow members with dropped sends and late
# acks; 10% ambient loss; 25% WAN ack tail).
def degraded1m_environment():
    """(FaultSchedule, loss, ack_late) of the degraded1m preset."""
    from consul_tpu.sim.faults import DegradedSet, FaultSchedule

    faults = FaultSchedule(
        degraded=(DegradedSet(frac=0.02, drop=0.5, late=0.6, seed=1),)
    )
    return faults, 0.10, 0.25


def degraded1m(seed: int = 0, n: int = 1_000_000, steps: int = 300) -> dict:
    """Lifeguard A/B at the headline scale: 1M nodes on WAN timing, 2%
    of members degraded (their sends drop, their acks run late), 10%
    ambient loss and a 25% WAN ack-tail — the slow-member environment
    of the Lifeguard paper.  Runs the SAME faulted universe twice (one
    jit trace each), Lifeguard on and off, and reports the
    false-positive suspicion rate, refute and incarnation-flap deltas:
    the simulator's first accuracy question rather than a speed one.

    ``n``/``steps`` scale down for CPU smoke runs (tests use n=256..1024).
    """
    import dataclasses as _dc

    from consul_tpu.models import LifeguardConfig
    from consul_tpu.sim.engine import run_lifeguard

    faults, loss, ack_late = degraded1m_environment()
    cfg = LifeguardConfig(
        n=n,
        subject=7 % n,
        subject_alive=True,
        loss=loss,
        ack_late=ack_late,
        profile=WAN,
        delivery="aggregate",
        lifeguard=True,
        faults=faults,
    )
    on = run_lifeguard(cfg, steps=steps, seed=seed, warmup=False)
    off = run_lifeguard(
        _dc.replace(cfg, lifeguard=False), steps=steps, seed=seed,
        warmup=False,
    )
    return {
        "scenario": "degraded1m",
        "n": n,
        "ticks": steps,
        "tick_ms": on.tick_ms,
        "fp_total_on": on.fp_total,
        "fp_total_off": off.fp_total,
        "fp_rate_on": on.fp_rate,
        "fp_rate_off": off.fp_rate,
        "fp_reduction": (
            1.0 - on.fp_total / off.fp_total if off.fp_total else None
        ),
        "flaps_on": on.flap_count,
        "flaps_off": off.flap_count,
        "refutes_on": on.refute_total,
        "refutes_off": off.refute_total,
        "mean_awareness_final": float(on.mean_awareness[-1]),
        "sim_rounds_per_sec": on.rounds_per_sec,
    }


SCENARIOS: dict[str, Callable[..., dict]] = {
    "dev3": dev3,
    "probe1k": probe1k,
    "event100k": event100k,
    "stream100k": stream100k,
    "geo100k": geo100k,
    "suspect1m": suspect1m,
    "multidc1m": multidc1m,
    "degraded1m": degraded1m,
}


def run_scenario(name: str, seed: int = 0, devices: int = None,
                 exchange: str = None, telemetry: bool = False,
                 policy: str = None) -> dict:
    """Run a preset by name.  ``devices`` shards the node axis over the
    first D mesh devices for the scenarios that support it (probe1k,
    event100k, stream100k, geo100k); asking it of any other preset is an error,
    not a silent single-chip run.  ``exchange`` picks the outbox transport of the
    sharded plane and therefore requires ``devices`` — same
    loud-never-silent contract.  ``telemetry`` runs the study with the
    in-scan metrics seam on (consul_tpu/obs) and adds the bridged
    /v1/agent/metrics-shaped snapshot under ``"metrics"`` (``cli sim
    --metrics``); presets without the seam reject it loudly too.
    ``policy`` picks the streamcast chunk-selection schedule (``cli
    sim stream100k --policy``); presets without the selection-policy
    seam reject it loudly — never a silently-ignored flag."""
    import inspect

    try:
        fn = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; choose from {sorted(SCENARIOS)}"
        ) from None
    if exchange and not devices:
        raise ValueError(
            "--exchange selects the sharded plane's outbox transport "
            "and requires --devices"
        )
    params = inspect.signature(fn).parameters
    if telemetry and "telemetry" not in params:
        raise ValueError(
            f"scenario {name!r} does not support --metrics"
        )
    if policy and "policy" not in params:
        raise ValueError(
            f"scenario {name!r} does not support --policy (the "
            "chunk-selection seam belongs to the streamcast plane)"
        )
    tele_kw = {"telemetry": True} if telemetry else {}
    pol_kw = {"policy": policy} if policy else {}
    if devices:
        if "devices" not in params:
            raise ValueError(
                f"scenario {name!r} does not support --devices"
            )
        return fn(seed=seed, devices=devices,
                  **({"exchange": exchange} if exchange else {}),
                  **tele_kw, **pol_kw)
    return fn(seed=seed, **tele_kw, **pol_kw)
