"""The BASELINE.json study configs as runnable presets.

Each scenario returns a summary dict via ``run_scenario(name)`` — the
programmatic entry point for the benchmark harness (and the CLI, once
the host agent plane lands).

  dev3        3-node LAN pool, single user-event broadcast (CPU ref)
  probe1k     1k-node SWIM probe/ack with 1% induced failure, fanout 3
  event100k   100k-node serf event broadcast, LAN timing, fanout 4,
              99% infection time
  suspect1m   1M-node suspicion/dead propagation, 30% loss, WAN profile
  multidc1m   1M-node 8-segment multi-DC epidemic broadcast, sharded
              across the device mesh
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import numpy as np

from consul_tpu.models import BroadcastConfig, SwimConfig
from consul_tpu.protocol import LAN, WAN
from consul_tpu.sim.engine import run_broadcast, run_swim


def dev3(seed: int = 0) -> dict:
    """BASELINE config 1: 3-node dev pool, one user event (CPU-scale ref).

    The 3-node `agent -dev` LAN pool of the reference; at this size the
    exact edge simulation is the only sensible mode."""
    cfg = BroadcastConfig(n=3, profile=LAN, delivery="edges")
    rep = run_broadcast(cfg, steps=10, seed=seed, warmup=False)
    return {"scenario": "dev3", **rep.summary()}


def probe1k(seed: int = 0) -> dict:
    """BASELINE config 2: 1k nodes, SWIM probe/ack, 1% induced failure.

    1% of 1000 nodes = 10 independent crash subjects, vmapped."""
    cfg = SwimConfig(n=1000, subject=0, loss=0.0, profile=LAN,
                     delivery="edges")
    # 1% of 1000 nodes = 10 subjects, run as independent studies (the
    # subject index only relabels nodes, so varying the seed is the
    # faithful ensemble).
    summaries = [
        run_swim(cfg, steps=200, seed=seed + s, warmup=False).summary()
        for s in range(10)
    ]
    first_sus = [s["first_suspect_ms"] for s in summaries]
    first_dead = [s["first_dead_ms"] for s in summaries]
    return {
        "scenario": "probe1k",
        "n": 1000,
        "subjects": len(summaries),
        "mean_first_suspect_ms": float(np.mean(first_sus)),
        "mean_first_dead_ms": float(np.mean(first_dead)),
    }


def event100k(seed: int = 0) -> dict:
    """BASELINE config 3: 100k-node event broadcast, LAN, fanout 4."""
    cfg = BroadcastConfig(n=100_000, fanout=4, profile=LAN,
                          delivery="aggregate")
    rep = run_broadcast(cfg, steps=100, seed=seed)
    return {"scenario": "event100k", **rep.summary()}


def suspect1m(seed: int = 0) -> dict:
    """BASELINE config 4: 1M-node suspicion/dead propagation, 30% loss,
    WAN timing."""
    cfg = SwimConfig(n=1_000_000, subject=42, loss=0.30, profile=WAN,
                     delivery="aggregate")
    # Suspicion min timeout at 1M WAN = 6*log10(1e6)*5s = 180s = 360
    # ticks; run past it so dead propagation is measured.
    rep = run_swim(cfg, steps=500, seed=seed)
    return {"scenario": "suspect1m", **rep.summary()}


def multidc1m(seed: int = 0) -> dict:
    """BASELINE config 5: 1M nodes in 8 segments (1 segment per device),
    epidemic broadcast sharded across the mesh."""
    from consul_tpu.parallel import make_mesh

    cfg = BroadcastConfig(n=1_000_000, fanout=4, profile=LAN,
                          delivery="aggregate")
    mesh = make_mesh()
    rep = run_broadcast(cfg, steps=100, seed=seed, sharded=True, mesh=mesh)
    return {
        "scenario": "multidc1m",
        "segments": int(mesh.devices.size),
        **rep.summary(),
    }


SCENARIOS: dict[str, Callable[..., dict]] = {
    "dev3": dev3,
    "probe1k": probe1k,
    "event100k": event100k,
    "suspect1m": suspect1m,
    "multidc1m": multidc1m,
}


def run_scenario(name: str, seed: int = 0) -> dict:
    try:
        fn = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; choose from {sorted(SCENARIOS)}"
        ) from None
    return fn(seed=seed)
