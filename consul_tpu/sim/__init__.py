"""Scan-based simulation engine, convergence metrics, scenario presets."""

# faults first: it is dependency-free and models.lifeguard pulls it in
# through this package's __init__, so it must be bound before engine
# (which imports the models) starts executing.
from consul_tpu.sim.faults import (
    ChurnWindow,
    DegradedSet,
    FaultSchedule,
    LossRamp,
    Partition,
)
from consul_tpu.sim.engine import (
    membership_scan,
    run_membership_sparse,
    sparse_membership_scan,
    multidc_scan,
    run_broadcast,
    run_lifeguard,
    run_membership,
    run_multidc,
    run_sweep,
    run_swim,
    broadcast_scan,
    lifeguard_scan,
    swim_scan,
    streamcast_scan,
    run_streamcast,
    sharded_broadcast_scan,
    sharded_membership_scan,
    sharded_sparse_membership_scan,
    sharded_streamcast_scan,
)
from consul_tpu.sim.metrics import (
    time_to_fraction,
    FalsePositiveReport,
    MembershipReport,
    MultiDCReport,
    BroadcastReport,
    SwimReport,
)
from consul_tpu.sim.scenarios import SCENARIOS, run_scenario

__all__ = [
    "ChurnWindow",
    "DegradedSet",
    "FaultSchedule",
    "FalsePositiveReport",
    "LossRamp",
    "Partition",
    "lifeguard_scan",
    "run_lifeguard",
    "membership_scan",
    "run_membership_sparse",
    "sparse_membership_scan",
    "run_membership",
    "MembershipReport",
    "run_broadcast",
    "run_multidc",
    "run_sweep",
    "run_swim",
    "broadcast_scan",
    "multidc_scan",
    "swim_scan",
    "streamcast_scan",
    "run_streamcast",
    "sharded_broadcast_scan",
    "sharded_membership_scan",
    "sharded_sparse_membership_scan",
    "sharded_streamcast_scan",
    "time_to_fraction",
    "BroadcastReport",
    "SwimReport",
    "SCENARIOS",
    "run_scenario",
]
