"""Scan-based simulation engine, convergence metrics, scenario presets."""

from consul_tpu.sim.engine import (
    membership_scan,
    run_membership_sparse,
    sparse_membership_scan,
    multidc_scan,
    run_broadcast,
    run_membership,
    run_multidc,
    run_swim,
    broadcast_scan,
    swim_scan,
)
from consul_tpu.sim.metrics import (
    time_to_fraction,
    MembershipReport,
    MultiDCReport,
    BroadcastReport,
    SwimReport,
)
from consul_tpu.sim.scenarios import SCENARIOS, run_scenario

__all__ = [
    "membership_scan",
    "run_membership_sparse",
    "sparse_membership_scan",
    "run_membership",
    "MembershipReport",
    "run_broadcast",
    "run_multidc",
    "run_swim",
    "broadcast_scan",
    "multidc_scan",
    "swim_scan",
    "time_to_fraction",
    "BroadcastReport",
    "SwimReport",
    "SCENARIOS",
    "run_scenario",
]
