"""Adversarial offered-load generators for the streaming planes.

Production event traffic is not a well-behaved homogeneous Poisson
stream: queues wake up with work already in them, payload sizes are
heavy-tailed, and publishers cluster on a handful of hot nodes.  This
module holds the pure schedule-shaping primitives that turn a clean
synthetic arrival schedule into those regimes — each one a pure
``jnp`` function of (key, schedule arrays, severity scalar), so a
severity can ride as a TRACED per-universe knob (consul_tpu/sweep)
exactly like the fault severities in :mod:`consul_tpu.sim.faults`:

  standing_backlog   pin the first B arrivals to tick 0 — the window
                     starts the run already holding work (the
                     bufferbloat regime: sustained load measured
                     against a queue that never drained).
  paced_ticks        staggered (constant-interval) birth ticks at the
                     same mean rate as the Poisson stream — the
                     deterministic offered load that measures a
                     capacity knee without Poisson burst noise.
  heavy_tail_sizes   per-event chunk counts from a Pareto(tail) draw
                     over [1, E]: mostly small events with occasional
                     full-width ones — ``tail`` is the Pareto tail
                     index (smaller = heavier); 0 disables (every
                     event uses all E chunks, the exactness default).
  hotspot_origins    re-originate a ``frac`` of the arrivals at one
                     hot node — the all-events-from-one-DC pattern the
                     geo bench showed is the hard case; 0 disables.

The disable values (backlog=0, tail=0.0, frac=0.0) are exact no-ops on
the schedule ARRAYS: the consuming program stays bit-equal to the
clean-stream program (the streamcast ``policy="uniform"`` exactness
discipline rides through these generators untouched).  Severity draws
come from the caller's salted keys, never from the gap/origin/name
streams, so enabling one regime never reshuffles another.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def standing_backlog(ev_tick: jax.Array, backlog: int) -> jax.Array:
    """Pin the first ``backlog`` schedule entries to tick 0.

    The remaining arrivals keep their staggered birth ticks, so the
    stream is "B events already in flight at t=0, then the ongoing
    arrival process" — a window that starts full instead of filling
    gradually.  ``backlog`` is static schedule structure (it decides
    WHICH entries move, not a rate); a backlog wider than the window
    overflows loudly at tick 0, never silently.
    """
    if backlog <= 0:
        return ev_tick
    k = ev_tick.shape[0]
    idx = jnp.arange(k, dtype=jnp.int32)
    return jnp.where(idx < backlog, 0, ev_tick)


def paced_ticks(k: int, rate) -> jax.Array:
    """int32[k] staggered birth ticks: event i is born at
    ``floor(i / rate)`` — one event every ``1/rate`` ticks, the same
    mean offered load as the Poisson stream but with ZERO burst
    variance.  A window overflows under this stream iff
    ``rate x slot lifetime`` really exceeds W (the deterministic
    capacity knee); under Poisson arrivals the same knee is smeared by
    burst noise.  ``rate`` enters as ordinary jnp arithmetic
    (sweepable), exactly like the Poisson gap derivation."""
    rate_f = jnp.maximum(jnp.asarray(rate, jnp.float32), 1e-6)
    idx = jnp.arange(k, dtype=jnp.float32)
    return jnp.floor(idx / rate_f).astype(jnp.int32)


def heavy_tail_sizes(key: jax.Array, k: int, e_max: int,
                     tail) -> jax.Array:
    """int32[k] per-event chunk counts in [1, e_max].

    ``tail`` > 0 draws Pareto(x_min=1, index=tail) sizes clipped to
    the static E ceiling — P(size >= s) = s**-tail, so tail=1 gives
    the classic mostly-1-chunk stream with occasional full-payload
    events.  ``tail`` enters as ordinary jnp arithmetic (sweepable);
    tail=0 returns every event at the full ``e_max`` — the exactness
    default, where the chunk-validity mask is all-True and the
    consuming program is bit-equal to the unmasked one.
    """
    u = jax.random.uniform(
        key, (k,), jnp.float32, minval=1e-7, maxval=1.0
    )
    tail_f = jnp.asarray(tail, jnp.float32)
    alpha = jnp.maximum(tail_f, 1e-6)
    # floor, not ceil: P(size >= s) = s**-tail exactly on the integer
    # support (ceil would map the whole (1, 2] mass to 2 and leave
    # P(size = 1) = 0 — no head, which defeats "mostly small").
    pareto = jnp.clip(
        jnp.floor(u ** (-1.0 / alpha)), 1.0, float(e_max)
    ).astype(jnp.int32)
    return jnp.where(tail_f > 0.0, pareto, jnp.int32(e_max))


def hotspot_origins(key: jax.Array, ev_origin: jax.Array, frac,
                    node: int) -> jax.Array:
    """Re-originate a ``frac`` of the arrivals at the hot ``node``.

    Each event independently publishes from ``node`` with probability
    ``frac`` (sweepable: it enters only as a comparison threshold);
    frac=0 keeps every origin untouched — including the draw itself,
    whose key is salted off the arrival stream, so the clean program
    never sees reshuffled origins.
    """
    u = jax.random.uniform(key, ev_origin.shape, jnp.float32)
    return jnp.where(
        u < jnp.asarray(frac, jnp.float32),
        jnp.int32(node), ev_origin,
    )
