"""Fault-injection schedules: the tunable environment of a study.

"Robust and Tuneable Family of Gossiping Algorithms" (PAPERS.md)
motivates treating the fault environment as a *parameter family* rather
than a fixed loss constant.  A :class:`FaultSchedule` is a static,
hashable description of that environment; every query is a pure
function of ``(schedule, tick[, key])`` built from ``jnp`` ops, so a
whole study — schedule included — compiles into one ``lax.scan`` /
XLA program with no host round-trips.

Primitives (each optional, all composable):

  LossRamp      piecewise-constant extra packet loss over time
                (e.g. a WAN brownout ramping 0% -> 40% -> healed)
  Partition     a DC/segment split: cross-segment edges drop with
                ``severity`` between ``start`` and ``heal`` ticks
  DegradedSet   a pseudo-random subset of nodes whose *sends* (and
                therefore their acks/nacks) drop with elevated
                probability — the slow-member population Lifeguard
                exists for
  ChurnWindow   a window during which each node is independently
                offline (restarting) with per-tick probability
  BandwidthSchedule
                piecewise per-link WAN capacity in bytes/tick (a
                bandwidth brownout): the geo plane (consul_tpu/geo)
                caps how many WAN message-bytes cross each segment
                pair per tick, with overflow counted loudly — the
                varying-bandwidth environment of "A State Transfer
                Method That Adapts to Network Bandwidth Variations in
                Geographic State Machine Replication" (PAPERS.md)

``compose`` merges two schedules; independent drop processes combine as
``1 - prod(1 - p_i)`` (evaluated in :func:`extra_loss_at` /
:func:`degraded_send_ok`), so composition is associative and
order-independent.  Parity of the combination math with scalar
expectations is pinned by tests/test_faults.py.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


def _concrete(x) -> bool:
    """True for host scalars; False for traced values (universe-sweep
    knobs, consul_tpu/sweep): evaluation-time short-circuits and
    validation apply only to values known before tracing."""
    return isinstance(x, (int, float, bool))


def _static_zero(x) -> bool:
    """Statically known to contribute nothing — safe to skip at trace
    time.  A traced value is never skipped (its run-time value decides)."""
    return _concrete(x) and x <= 0.0


@dataclasses.dataclass(frozen=True)
class LossRamp:
    """Piecewise-constant extra loss: ``pieces`` is a sorted tuple of
    (start_tick, loss); loss is 0 before the first piece and each piece
    holds until the next one starts (the last piece holds forever).

    ``scale`` multiplies every piece's loss (clipped back to [0, 1]) —
    the severity knob of a fault-matrix sweep: one static ramp shape,
    a per-universe traced severity."""

    pieces: tuple[tuple[int, float], ...]
    scale: float = 1.0

    def __post_init__(self):
        starts = [s for s, _ in self.pieces]
        if starts != sorted(starts):
            raise ValueError(f"LossRamp pieces must be sorted, got {starts}")
        for _, p in self.pieces:
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"loss {p} outside [0, 1]")
        if _concrete(self.scale) and self.scale < 0.0:
            raise ValueError(f"scale {self.scale} must be >= 0")


@dataclasses.dataclass(frozen=True)
class Partition:
    """Cross-segment edges drop with ``severity`` in [start, heal).
    Node i belongs to segment ``i * segments // n``."""

    start: int
    heal: int
    segments: int = 2
    severity: float = 1.0


@dataclasses.dataclass(frozen=True)
class DegradedSet:
    """A pseudo-random ``frac`` of nodes that are persistently slow:
    their sends drop with extra probability ``drop``, and the probes
    THEY perform see the ack arrive late (past the unscaled probe
    window) with probability ``late`` — the slow-member population
    Lifeguard exists for (a late ack is only a failure to an observer
    whose NHM hasn't stretched its window yet).  Membership is a pure
    function of (seed, n): deterministic across runs, devices, and
    delivery modes."""

    frac: float
    drop: float = 0.5
    late: float = 0.0
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class ChurnWindow:
    """During [start, end) each node is independently offline with
    probability ``p_offline`` per tick (a restart storm, not a crash:
    the node is back in the next draw)."""

    start: int
    end: int
    p_offline: float


@dataclasses.dataclass(frozen=True)
class BandwidthSchedule:
    """Piecewise per-link WAN capacity: ``pieces`` is a sorted tuple of
    (start_tick, bytes_per_tick); before the first piece the link is
    unconstrained (the consumer's static base capacity applies) and
    each piece holds until the next one starts (the last holds
    forever).  ``src``/``dst`` select one directed segment link (-1 =
    every link), so a single schedule can brown out one WAN path while
    the rest of the mesh stays healthy.

    ``scale`` multiplies every piece's capacity — the severity knob of
    a brownout sweep: one static schedule shape, a per-universe traced
    severity (smaller scale = harder brownout).  Schedules compose by
    per-link MINIMUM (the tightest constraint wins), and the consumer
    clips the result to its static base capacity, so a traced scale
    can never admit more than the static ceiling."""

    pieces: tuple[tuple[int, float], ...]
    src: int = -1
    dst: int = -1
    scale: float = 1.0

    def __post_init__(self):
        starts = [s for s, _ in self.pieces]
        if starts != sorted(starts):
            raise ValueError(
                f"BandwidthSchedule pieces must be sorted, got {starts}"
            )
        for _, cap in self.pieces:
            if cap < 0:
                raise ValueError(f"capacity {cap} must be >= 0 bytes/tick")
        if _concrete(self.scale) and self.scale < 0.0:
            raise ValueError(f"scale {self.scale} must be >= 0")


@dataclasses.dataclass(frozen=True)
class FaultSchedule:
    ramps: tuple[LossRamp, ...] = ()
    partitions: tuple[Partition, ...] = ()
    degraded: tuple[DegradedSet, ...] = ()
    churn: tuple[ChurnWindow, ...] = ()
    bandwidth: tuple[BandwidthSchedule, ...] = ()

    def compose(self, other: "FaultSchedule") -> "FaultSchedule":
        """Union of fault processes; independent drops multiply out at
        evaluation time (bandwidth constraints combine by min)."""
        return FaultSchedule(
            ramps=self.ramps + other.ramps,
            partitions=self.partitions + other.partitions,
            degraded=self.degraded + other.degraded,
            churn=self.churn + other.churn,
            bandwidth=self.bandwidth + other.bandwidth,
        )

    @property
    def has_faults(self) -> bool:
        return bool(self.ramps or self.partitions or self.degraded
                    or self.churn or self.bandwidth)


# ---------------------------------------------------------------------------
# Pure evaluators.  ``tick`` may be a traced scalar; the schedule itself
# is static, so all tuple-derived arrays fold into XLA constants.
# ---------------------------------------------------------------------------


def extra_loss_at(sched: FaultSchedule, tick: jax.Array) -> jax.Array:
    """float32 scalar: extra loss from all ramps at ``tick``, combined
    as independent drop processes."""
    keep = jnp.float32(1.0)
    for ramp in sched.ramps:
        starts = jnp.asarray([s for s, _ in ramp.pieces], jnp.int32)
        losses = jnp.asarray(
            [0.0] + [p for _, p in ramp.pieces], jnp.float32
        )
        losses = jnp.clip(
            losses * jnp.asarray(ramp.scale, jnp.float32), 0.0, 1.0
        )
        idx = jnp.searchsorted(starts, tick, side="right")
        keep = keep * (1.0 - losses[idx])
    return 1.0 - keep


def combine_loss(a, b):
    """Combined drop probability of two independent loss processes."""
    return 1.0 - (1.0 - a) * (1.0 - b)


def _members(d: DegradedSet, n: int) -> jax.Array:
    """bool[n]: the set's membership — THE single definition all
    degraded evaluators share, so send-drop, late-ack and the reporting
    mask can never describe different node populations."""
    return jax.random.bernoulli(jax.random.PRNGKey(d.seed), d.frac, (n,))


def degraded_send_ok(sched: FaultSchedule, n: int) -> jax.Array:
    """float32[n]: per-node send survival multiplier (1.0 = healthy).
    A node in several DegradedSets drops independently per set."""
    ok = jnp.ones((n,), jnp.float32)
    for d in sched.degraded:
        if _static_zero(d.frac):
            continue
        ok = ok * jnp.where(_members(d, n), 1.0 - d.drop, 1.0)
    return ok


def degraded_mask(sched: FaultSchedule, n: int) -> jax.Array:
    """bool[n]: nodes degraded by ANY set (for reporting)."""
    mask = jnp.zeros((n,), bool)
    for d in sched.degraded:
        if _static_zero(d.frac):
            continue
        mask = mask | _members(d, n)
    return mask


def degraded_late(sched: FaultSchedule, n: int) -> jax.Array:
    """float32[n]: per-node probability that a probe performed by the
    node sees its ack arrive late (slow local processing).  Independent
    late processes across sets combine like drops."""
    keep = jnp.ones((n,), jnp.float32)
    for d in sched.degraded:
        if _static_zero(d.frac) or _static_zero(d.late):
            continue
        keep = keep * jnp.where(_members(d, n), 1.0 - d.late, 1.0)
    return 1.0 - keep


def segment_ids(partition: Partition, n: int) -> jax.Array:
    """int32[n]: which side of the split each node is on."""
    return (
        jnp.arange(n, dtype=jnp.int32) * partition.segments // n
    ).astype(jnp.int32)


def partition_severity_at(partition: Partition, tick: jax.Array) -> jax.Array:
    """float32 scalar: the partition's drop severity at ``tick`` (0
    outside its window — healed)."""
    active = (tick >= partition.start) & (tick < partition.heal)
    # asarray: severity is a sweepable per-universe knob.
    return jnp.where(
        active, jnp.asarray(partition.severity, jnp.float32), 0.0
    )


def edge_block_prob(
    sched: FaultSchedule, tick: jax.Array, src: jax.Array, dst: jax.Array,
    n: int,
) -> jax.Array:
    """Per-edge drop probability from all partitions, for explicit
    (src, dst) index arrays (edges-mode delivery).  Shapes broadcast."""
    keep = jnp.ones(jnp.broadcast_shapes(src.shape, dst.shape), jnp.float32)
    for part in sched.partitions:
        seg = segment_ids(part, n)
        cross = seg[src] != seg[dst]
        sev = partition_severity_at(part, tick)
        keep = keep * jnp.where(cross, 1.0 - sev, 1.0)
    return 1.0 - keep


def offline_prob_at(sched: FaultSchedule, tick: jax.Array) -> jax.Array:
    """float32 scalar: per-node offline probability at ``tick``
    (churn windows combine independently)."""
    keep = jnp.float32(1.0)
    for w in sched.churn:
        active = (tick >= w.start) & (tick < w.end)
        keep = keep * jnp.where(active, 1.0 - w.p_offline, 1.0)
    return 1.0 - keep


def online_mask(
    sched: FaultSchedule, key: jax.Array, tick: jax.Array, n: int
) -> jax.Array:
    """bool[n]: nodes participating this tick (True = online).

    The churn draw rides the owned per-(round, node) streams
    (ops/sampling.py): node i's coin depends only on ``(key, i)``."""
    if not sched.churn:
        return jnp.ones((n,), bool)
    from consul_tpu.ops.sampling import owned_uniform

    p_off = offline_prob_at(sched, tick)
    return owned_uniform(key, jnp.arange(n, dtype=jnp.int32)) >= p_off


def _link_mask(bs: BandwidthSchedule, segments: int):
    """Host-built bool[S, S]: the directed links a schedule constrains
    (``src``/``dst`` are static segment selectors)."""
    import numpy as np

    mask = np.ones((segments, segments), bool)
    if bs.src >= 0:
        if bs.src >= segments:
            raise ValueError(
                f"BandwidthSchedule src={bs.src} outside [0, {segments})"
            )
        mask &= np.arange(segments)[:, None] == bs.src
    if bs.dst >= 0:
        if bs.dst >= segments:
            raise ValueError(
                f"BandwidthSchedule dst={bs.dst} outside [0, {segments})"
            )
        mask &= np.arange(segments)[None, :] == bs.dst
    return mask


def link_capacity_at(
    sched: FaultSchedule, tick: jax.Array, segments: int, base: float
) -> jax.Array:
    """float32[S, S]: per-directed-link capacity in bytes/tick at
    ``tick``.  ``base`` is the static per-link ceiling (the unfaulted
    capacity); schedules only ever tighten it — constraints combine by
    per-link minimum and the result is clipped to [0, base], so a
    traced ``scale`` can never admit past the static bound the
    consumer's slot planes are sized for."""
    cap = jnp.full((segments, segments), base, jnp.float32)
    for bs in sched.bandwidth:
        starts = jnp.asarray([s for s, _ in bs.pieces], jnp.int32)
        # Index 0 is the pre-schedule sentinel (unconstrained: the base
        # applies); pieces are scaled by the (possibly traced) severity.
        vals = jnp.asarray(
            [0.0] + [c for _, c in bs.pieces], jnp.float32
        ) * jnp.asarray(bs.scale, jnp.float32)
        idx = jnp.searchsorted(starts, tick, side="right")
        val = jnp.where(idx == 0, jnp.float32(base), vals[idx])
        mask = jnp.asarray(_link_mask(bs, segments), jnp.bool_)
        cap = jnp.where(mask, jnp.minimum(cap, val), cap)
    return jnp.clip(cap, 0.0, jnp.float32(base))
